"""Batch elliptic-curve arithmetic on TPU (secp256k1 and SM2 share one path).

Replaces the reference's per-signature CPU EC stack (wedpr-crypto Rust FFI
behind bcos-crypto — `wedpr_secp256k1_verify` at
bcos-crypto/bcos-crypto/signature/secp256k1/Secp256k1Crypto.cpp:57, SM2 at
signature/sm2/SM2Crypto.cpp:29-91) with batch Jacobian-coordinate kernels over
the limb-major field arithmetic in :mod:`fisco_bcos_tpu.ops.limb`.

TPU-first design:
- A point is an (X, Y, Z) tuple of ``[16, T]`` limb-major arrays in the
  curve's field domain (plain for the pseudo-Mersenne fast path, Montgomery
  for SM2); Z == 0 encodes infinity. The batch lives in the minor axis so
  every op runs at full VPU lane utilization.
- All group ops are branch-free: exceptional cases (infinity operands,
  P == Q, P == -Q) are resolved with lane-wise selects — one compiled
  program serves honest and adversarial lanes alike (consensus code must
  not diverge per lane).
- ``dual_mul_windowed`` computes u1*G + u2*Q with 4-bit windows and one
  shared doubling chain (Shamir): a 15-entry runtime Jacobian table for Q,
  and a host-precomputed affine table {c*G} so G contributions are cheap
  mixed (Z=1) additions with no runtime table build. This replaces round
  1's bit-at-a-time ladder (256 doublings + 256 full additions) with 256
  doublings + 64 full + 64 mixed additions.
- The whole ladder is a ``lax.scan`` over 64 window steps; table selects
  are 15-way masked chains (schedule identical on every lane).

The same functions run inside the Pallas TPU kernels (see
:mod:`fisco_bcos_tpu.ops.pallas_ec`) and under plain XLA on CPU; integer
semantics make both paths bit-identical — mandatory for consensus.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.ref.ecdsa import SECP256K1, SM2_CURVE, Curve, point_add
from . import limb
from .limb import (
    FoldField,
    MontField,
    const_rows,
    eq,
    is_zero,
    lt,
    make_fold_field,
    make_mont_field,
    select,
    sub_borrow,
)

_R = 1 << 256
WINDOW = 4
N_WINDOWS = 256 // WINDOW  # 64


@dataclass(frozen=True)
class CurveOps:
    """Static device context for one short-Weierstrass curve."""

    name: str
    curve: Curve
    F: FoldField | MontField  # field of the curve prime p
    Fn: FoldField | None  # scalar field mod n (None -> plain-limb helpers)
    a_is_zero: bool
    a_enc: np.ndarray  # a in field domain, [16]
    b_enc: np.ndarray  # b in field domain, [16]
    p_limbs: np.ndarray = field(repr=False)
    n_limbs: np.ndarray = field(repr=False)

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        return isinstance(other, CurveOps) and other.name == self.name


def _make_curve_ops(c: Curve) -> CurveOps:
    # Pseudo-Mersenne fast path when p = 2^256 - small (secp256k1); generic
    # Montgomery otherwise (SM2's p has a 225-bit complement).
    F = make_fold_field(c.p) if _R - c.p < 1 << 132 else make_mont_field(c.p)
    Fn = make_fold_field(c.n) if _R - c.n < 1 << 132 else None
    return CurveOps(
        name=c.name,
        curve=c,
        F=F,
        Fn=Fn,
        a_is_zero=c.a == 0,
        a_enc=F.enc(c.a),
        b_enc=F.enc(c.b),
        p_limbs=limb.int_to_rows(c.p),
        n_limbs=limb.int_to_rows(c.n),
    )


SECP256K1_OPS = _make_curve_ops(SECP256K1)
SM2_OPS = _make_curve_ops(SM2_CURVE)


# ---------------------------------------------------------------------------
# Jacobian group law (field domain, branch-free)
# ---------------------------------------------------------------------------


def jac_double(P, C: CurveOps):
    """dbl-2007-bl. Safe without selects: doubling infinity (Z=0) or a
    2-torsion point (Y=0) yields Z3 = 0 — the correct group result."""
    X, Y, Z = P
    F = C.F
    xx = F.sqr(X)
    yy = F.sqr(Y)
    yyyy = F.sqr(yy)
    zz = F.sqr(Z)
    t = F.sqr(F.add(X, yy))
    s = F.sub(F.sub(t, xx), yyyy)
    s = F.add(s, s)  # S = 2((X+YY)^2 - XX - YYYY)
    m = F.add(F.add(xx, xx), xx)  # 3*XX
    if not C.a_is_zero:
        m = F.add(m, F.mul(const_rows(C.a_enc, X), F.sqr(zz)))
    x3 = F.sub(F.sqr(m), F.add(s, s))
    y8 = F.add(yyyy, yyyy)
    y8 = F.add(y8, y8)
    y8 = F.add(y8, y8)
    y3 = F.sub(F.mul(m, F.sub(s, x3)), y8)
    z3 = F.sub(F.sub(F.sqr(F.add(Y, Z)), yy), zz)
    return x3, y3, z3


def jac_add(P, Q, C: CurveOps):
    """add-2007-bl with full exceptional-case handling via selects."""
    X1, Y1, Z1 = P
    X2, Y2, Z2 = Q
    F = C.F
    z1z1 = F.sqr(Z1)
    z2z2 = F.sqr(Z2)
    u1 = F.mul(X1, z2z2)
    u2 = F.mul(X2, z1z1)
    s1 = F.mul(F.mul(Y1, Z2), z2z2)
    s2 = F.mul(F.mul(Y2, Z1), z1z1)
    h = F.sub(u2, u1)
    rr = F.sub(s2, s1)
    h2 = F.add(h, h)
    i = F.sqr(h2)
    j = F.mul(h, i)
    r2 = F.add(rr, rr)
    v = F.mul(u1, i)
    x3 = F.sub(F.sub(F.sqr(r2), j), F.add(v, v))
    s1j = F.mul(s1, j)
    y3 = F.sub(F.mul(r2, F.sub(v, x3)), F.add(s1j, s1j))
    z3 = F.mul(F.sub(F.sub(F.sqr(F.add(Z1, Z2)), z1z1), z2z2), h)
    inf1 = is_zero(Z1)
    inf2 = is_zero(Z2)
    same = is_zero(h) & is_zero(rr) & ~inf1 & ~inf2
    dx, dy, dz = jac_double(P, C)
    x = select(inf1, X2, select(inf2, X1, select(same, dx, x3)))
    y = select(inf1, Y2, select(inf2, Y1, select(same, dy, y3)))
    z = select(inf1, Z2, select(inf2, Z1, select(same, dz, z3)))
    return x, y, z


def jac_add_mixed(P, A, C: CurveOps):
    """P + (x2, y2) for affine A (Z2 = 1, A must not be infinity) — madd,
    7M+4S vs the 11M+5S full addition. Exceptional cases via selects."""
    X1, Y1, Z1 = P
    X2, Y2 = A
    F = C.F
    z1z1 = F.sqr(Z1)
    u2 = F.mul(X2, z1z1)
    s2 = F.mul(F.mul(Y2, Z1), z1z1)
    h = F.sub(u2, X1)
    hh = F.sqr(h)
    i = F.add(hh, hh)
    i = F.add(i, i)  # 4*HH
    j = F.mul(h, i)
    rr = F.sub(s2, Y1)
    rr = F.add(rr, rr)  # 2*(S2-Y1)
    v = F.mul(X1, i)
    x3 = F.sub(F.sub(F.sqr(rr), j), F.add(v, v))
    y1j = F.mul(Y1, j)
    y3 = F.sub(F.mul(rr, F.sub(v, x3)), F.add(y1j, y1j))
    z3 = F.sub(F.sub(F.sqr(F.add(Z1, h)), z1z1), hh)
    inf1 = is_zero(Z1)
    one = C.F.one(X1)
    same = is_zero(h) & is_zero(rr) & ~inf1
    dx, dy, dz = jac_double(P, C)
    x = select(inf1, X2, select(same, dx, x3))
    y = select(inf1, Y2, select(same, dy, y3))
    z = select(inf1, one, select(same, dz, z3))
    return x, y, z


def jac_infinity(like: jax.Array):
    """Point at infinity: (1, 1, 0) in any domain-encoding (Z=0 is the flag;
    X/Y values are never read for infinity lanes)."""
    z = jnp.zeros_like(like)
    one = jnp.concatenate([jnp.ones_like(like[:1]), z[1:]], axis=0)
    return one, one, z


def jac_to_affine(P, C: CurveOps):
    """(X, Y, Z) -> (x, y, inf_mask); affine coords stay in the field domain.

    Infinity lanes get x = y = 0 (F.inv(0) == 0)."""
    X, Y, Z = P
    F = C.F
    zinv = F.inv(Z)
    zi2 = F.sqr(zinv)
    zi3 = F.mul(zi2, zinv)
    return F.mul(X, zi2), F.mul(Y, zi3), is_zero(Z)


def on_curve(x_enc: jax.Array, y_enc: jax.Array, C: CurveOps) -> jax.Array:
    """y^2 == x^3 + a*x + b (field domain) -> bool[T]."""
    F = C.F
    rhs = F.mul(F.sqr(x_enc), x_enc)
    if not C.a_is_zero:
        rhs = F.add(rhs, F.mul(const_rows(C.a_enc, x_enc), x_enc))
    rhs = F.add(rhs, const_rows(C.b_enc, x_enc))
    return eq(F.sqr(y_enc), rhs)


# ---------------------------------------------------------------------------
# Scalar-range helpers (plain-domain limbs)
# ---------------------------------------------------------------------------


def valid_scalar(x: jax.Array, C: CurveOps) -> jax.Array:
    """1 <= x < n (signature component range check)."""
    return ~is_zero(x) & lt(x, const_rows(C.n_limbs, x))


def reduce_mod_n(z: jax.Array, C: CurveOps) -> jax.Array:
    """z mod n for z < 2n (single conditional subtract; n > 2^255 for both
    curves, so any 256-bit z qualifies)."""
    return limb.cond_sub(z, C.n_limbs)


def add_mod_n(a: jax.Array, b: jax.Array, C: CurveOps) -> jax.Array:
    """(a + b) mod n for plain a, b < n (no field object needed)."""
    return limb.cond_sub(limb.add_widen(a, b), C.n_limbs)


# ---------------------------------------------------------------------------
# Fixed-base comb table for G (host-precomputed from curve constants)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def g_comb_table(name: str) -> np.ndarray:
    """[30, 16] uint32: field-domain affine coordinates of c * G for window
    value c in 1..15 — rows 0..14 hold the x coordinates, rows 15..29 the y
    coordinates (the 30-row leading axis keeps the 16-limb axis off the TPU
    lane dimension).

    G is a compile-time constant, so its window table is precomputed on the
    host in affine form — the ladder adds G contributions with cheap mixed
    (Z=1) additions and no runtime table build. The table is
    position-independent: in the MSB-first shared-doubling ladder each
    window's contribution picks up its 2^(4i) factor from the remaining
    doublings, exactly like the Q term."""
    C = {SECP256K1_OPS.name: SECP256K1_OPS, SM2_OPS.name: SM2_OPS}[name]
    c = C.curve
    tab = np.zeros((30, limb.LIMBS), dtype=np.uint32)
    acc = None
    for k in range(1, 16):
        acc = point_add(c, acc, (c.gx, c.gy))
        assert acc is not None  # k*G is never infinity (k < n)
        tab[k - 1] = C.F.enc(acc[0])
        tab[15 + k - 1] = C.F.enc(acc[1])
    return tab


LIMBS_PER_SCALAR = 16


def window_at(k: jax.Array, wi: jax.Array) -> jax.Array:
    """4-bit window ``wi`` (traced scalar, 0 = LSB) of [16, T] plain limbs ->
    [T] uint32 in 0..15.

    Row fetch is a 16-way masked chain on the static limb index and the
    sub-limb shift is by a traced broadcast scalar — no gather, no
    dynamic_slice, so the same code lowers under Mosaic (Pallas TPU), where
    ``lax.scan`` over a precomputed [64, T] window array would not (its xs
    slicing needs dynamic_slice)."""
    li = wi // (16 // WINDOW)  # limb index 0..15
    sh = (wi % (16 // WINDOW)).astype(jnp.uint32) * WINDOW
    r = limb.row(k, 0)
    for j in range(1, LIMBS_PER_SCALAR):
        r = jnp.where(li == j, limb.row(k, j), r)
    return (r >> sh) & np.uint32(0xF)


def scalar_windows(k: jax.Array) -> jax.Array:
    """[16, T] plain limbs -> [64, T] 4-bit windows, LSB-first order (the
    scan-shape window precompute; plain-XLA path only)."""
    rep = jnp.repeat(k, 16 // WINDOW, axis=0)  # [64, T]
    shifts = limb.dev_vec((np.arange(N_WINDOWS) % (16 // WINDOW)) * WINDOW)
    return (rep >> shifts[:, None]) & np.uint32(0xF)


def _point_table_list(t1, C: CurveOps):
    """Window table of k*P for k = 1..15 as a 15-entry Python list of
    (x, y, z) tuples — 14 unrolled additions (Mosaic shape: no scan-stacking,
    Pallas TPU has no dynamic_update_slice for scan ys outputs)."""
    tab = [t1]
    for _ in range(14):
        tab.append(jac_add(tab[-1], t1, C))
    return tab


def _point_table_scan(t1, C: CurveOps):
    """Same table as three stacked [15, 16, T] arrays via a 14-step scan —
    the compact HLO shape for plain XLA (fast CPU compiles)."""

    def step(prev, _):
        nxt = jac_add(prev, t1, C)
        return nxt, nxt

    _, rest = lax.scan(step, t1, None, length=14)
    tq_x = jnp.concatenate([t1[0][None], rest[0]], axis=0)
    tq_y = jnp.concatenate([t1[1][None], rest[1]], axis=0)
    tq_z = jnp.concatenate([t1[2][None], rest[2]], axis=0)
    return tq_x, tq_y, tq_z


def _select15(tab, w: jax.Array):
    """tab: 15 entries (list of arrays/tuples, or a [15, ..., T] stacked
    array), w [T] in 0..15 -> tab[w-1] (w==0 lanes get tab[0], callers must
    mask). 15-way masked chain — branch-free."""
    sel = tab[0]
    for c in range(2, 16):
        sel = select(w == c, tab[c - 1], sel)
    return sel


def dual_mul_windowed(k1, k2, Q, C: CurveOps, g_table: jax.Array):
    """k1*G + k2*Q — the ECDSA/SM2 verification kernel.

    k1, k2: [16, T] plain-domain scalars (< n); Q: (x, y) field-domain affine
    (not infinity; garbage lanes are fine — callers mask validity).
    g_table: device copy of :func:`g_comb_table` ([30, 16]).

    Schedule: 64 window steps, each 4 doublings + one full addition (runtime
    Q table) + one mixed addition (affine G table), all lane-uniform. The
    loop/table trace shape follows :func:`limb.is_mosaic_trace` (fori +
    where-chains under Pallas, compact scans under plain XLA) — outputs are
    bit-identical either way.
    """
    F = C.F
    one = F.one(k1)
    t1 = (Q[0], Q[1], one)
    acc0 = jac_infinity(k1)

    if limb.is_mosaic_trace():
        tq = _point_table_list(t1, C)
        # G table as 15-entry lists of [16, 1] columns (affine x, y) —
        # static slices + reshape, not g_table[c] (no dynamic_slice in Mosaic)
        tg_x = [
            lax.slice_in_dim(g_table, c, c + 1, axis=0).reshape(16, 1)
            for c in range(15)
        ]
        tg_y = [
            lax.slice_in_dim(g_table, 15 + c, 16 + c, axis=0).reshape(16, 1)
            for c in range(15)
        ]

        def step(i, acc):
            wi = 63 - i  # MSB-first
            w1_i = window_at(k1, wi)
            w2_i = window_at(k2, wi)
            for _ in range(WINDOW):
                acc = jac_double(acc, C)
            qx, qy, qz = _select15(tq, w2_i)
            added = jac_add(acc, (qx, qy, qz), C)
            acc = select(w2_i == 0, acc, added)
            gx = _select15(tg_x, w1_i)  # [16, T]
            gy = _select15(tg_y, w1_i)
            madded = jac_add_mixed(acc, (gx, gy), C)
            acc = select(w1_i == 0, acc, madded)
            return acc

        return lax.fori_loop(0, N_WINDOWS, step, acc0)

    tq_x, tq_y, tq_z = _point_table_scan(t1, C)
    w1 = scalar_windows(k1)[::-1]  # MSB-first [64, T]
    w2 = scalar_windows(k2)[::-1]

    def sstep(acc, xs):
        w1_i, w2_i = xs
        for _ in range(WINDOW):
            acc = jac_double(acc, C)
        added = jac_add(
            acc, (_select15(tq_x, w2_i), _select15(tq_y, w2_i), _select15(tq_z, w2_i)), C
        )
        acc = select(w2_i == 0, acc, added)
        gx = _select15(g_table[:15][:, :, None], w1_i)  # [16, T]
        gy = _select15(g_table[15:][:, :, None], w1_i)
        madded = jac_add_mixed(acc, (gx, gy), C)
        acc = select(w1_i == 0, acc, madded)
        return acc, None

    acc, _ = lax.scan(sstep, acc0, (w1, w2))
    return acc


def scalar_mul(k, P, C: CurveOps):
    """k*P for field-domain affine P — windowed, no G-comb (generic point).

    Used by tests and non-hot paths; the hot kernels go through
    :func:`dual_mul_windowed`."""
    F = C.F
    one = F.one(k)
    t1 = (P[0], P[1], one)

    if limb.is_mosaic_trace():
        tq = _point_table_list(t1, C)

        def step(i, acc):
            w_i = window_at(k, 63 - i)
            for _ in range(WINDOW):
                acc = jac_double(acc, C)
            added = jac_add(acc, _select15(tq, w_i), C)
            return select(w_i == 0, acc, added)

        return lax.fori_loop(0, N_WINDOWS, step, jac_infinity(k))

    tq_x, tq_y, tq_z = _point_table_scan(t1, C)
    w = scalar_windows(k)[::-1]

    def sstep(acc, w_i):
        for _ in range(WINDOW):
            acc = jac_double(acc, C)
        added = jac_add(
            acc, (_select15(tq_x, w_i), _select15(tq_y, w_i), _select15(tq_z, w_i)), C
        )
        return select(w_i == 0, acc, added), None

    acc, _ = lax.scan(sstep, jac_infinity(k), w)
    return acc


def generator_affine(C: CurveOps, like: jax.Array):
    """The curve generator (field domain) broadcast over T."""
    return (
        const_rows(C.F.enc(C.curve.gx), like),
        const_rows(C.F.enc(C.curve.gy), like),
    )


# Re-exported plain-limb helpers used by the signature kernels
__all__ = [
    "CurveOps",
    "SECP256K1_OPS",
    "SM2_OPS",
    "jac_double",
    "jac_add",
    "jac_add_mixed",
    "jac_infinity",
    "jac_to_affine",
    "on_curve",
    "valid_scalar",
    "reduce_mod_n",
    "add_mod_n",
    "g_comb_table",
    "window_at",
    "dual_mul_windowed",
    "scalar_mul",
    "generator_affine",
    "eq",
    "is_zero",
    "lt",
    "select",
    "sub_borrow",
]
