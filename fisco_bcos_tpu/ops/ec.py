"""Batch elliptic-curve arithmetic on TPU (secp256k1 and SM2 share one path).

Replaces the reference's per-signature CPU EC stack (wedpr-crypto Rust FFI
behind bcos-crypto — `wedpr_secp256k1_verify` at
bcos-crypto/bcos-crypto/signature/secp256k1/Secp256k1Crypto.cpp:57, SM2 at
signature/sm2/SM2Crypto.cpp:29-91) with batch Jacobian-coordinate kernels over
the 256-bit Montgomery limb arithmetic in :mod:`fisco_bcos_tpu.ops.bigint`.

Design notes (TPU-first):
- A point is a (X, Y, Z) tuple of ``[..., 16]`` limb arrays in the Montgomery
  domain of the curve prime; Z == 0 encodes the point at infinity.
- All group ops are branch-free: exceptional cases (infinity operands,
  P == Q, P == -Q) are resolved with lane-wise selects, so one compiled
  program serves every lane of the batch — consensus-critical code must not
  diverge per lane.
- Scalar multiplication is an MSB-first double-and-add `lax.scan` over the 256
  scalar bits; u1*G + u2*Q uses Shamir's trick (one shared doubling chain).
  The schedule is identical for every lane; only selects depend on data.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.ref.ecdsa import SECP256K1, SM2_CURVE, Curve
from . import bigint
from .bigint import (
    Modulus,
    _const,
    _sub_with_borrow,
    add_mod,
    eq,
    from_mont,
    geq,
    is_zero,
    make_modulus,
    mont_inv,
    mont_mul,
    mont_pow,
    mont_sqr,
    select,
    sub_mod,
    to_mont,
)

_R = 1 << 256


@dataclass(frozen=True)
class CurveCtx:
    """Device constants for one short-Weierstrass curve (static under jit)."""

    name: str
    p: Modulus
    n: Modulus
    a_is_zero: bool
    a_m: np.ndarray  # a  in Montgomery(p) domain, [16]
    b_m: np.ndarray  # b  in Montgomery(p) domain, [16]
    gx_m: np.ndarray  # G.x in Montgomery(p) domain, [16]
    gy_m: np.ndarray  # G.y in Montgomery(p) domain, [16]
    curve: Curve

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        return isinstance(other, CurveCtx) and other.name == self.name


def make_curve_ctx(c: Curve) -> CurveCtx:
    def to_m(x: int) -> np.ndarray:
        return bigint.int_to_limbs(x * _R % c.p)

    return CurveCtx(
        name=c.name,
        p=make_modulus(c.p),
        n=make_modulus(c.n),
        a_is_zero=c.a == 0,
        a_m=to_m(c.a),
        b_m=to_m(c.b),
        gx_m=to_m(c.gx),
        gy_m=to_m(c.gy),
        curve=c,
    )


SECP256K1_CTX = make_curve_ctx(SECP256K1)
SM2_CTX = make_curve_ctx(SM2_CURVE)


# ---------------------------------------------------------------------------
# Jacobian group law (Montgomery domain, branch-free)
# ---------------------------------------------------------------------------


def jac_double(P, ctx: CurveCtx):
    """dbl-2007-bl; 8 sqr + 2 mul (1 mul saved when a == 0).

    Safe without selects: doubling infinity (Z=0) or a 2-torsion point (Y=0)
    yields Z3 = 0, i.e. infinity, which is the correct group result.
    """
    X, Y, Z = P
    p = ctx.p
    xx = mont_sqr(X, p)
    yy = mont_sqr(Y, p)
    yyyy = mont_sqr(yy, p)
    zz = mont_sqr(Z, p)
    t = mont_sqr(add_mod(X, yy, p), p)
    s = sub_mod(sub_mod(t, xx, p), yyyy, p)
    s = add_mod(s, s, p)  # S = 2((X+YY)^2 - XX - YYYY)
    m = add_mod(add_mod(xx, xx, p), xx, p)  # 3*XX
    if not ctx.a_is_zero:
        m = add_mod(m, mont_mul(_const(ctx.a_m, X), mont_sqr(zz, p), p), p)
    x3 = sub_mod(mont_sqr(m, p), add_mod(s, s, p), p)
    y8 = add_mod(yyyy, yyyy, p)
    y8 = add_mod(y8, y8, p)
    y8 = add_mod(y8, y8, p)
    y3 = sub_mod(mont_mul(m, sub_mod(s, x3, p), p), y8, p)
    z3 = sub_mod(sub_mod(mont_sqr(add_mod(Y, Z, p), p), yy, p), zz, p)
    return x3, y3, z3


def jac_add(P, Q, ctx: CurveCtx):
    """add-2007-bl with full exceptional-case handling via selects.

    Handles P or Q at infinity, P == Q (falls back to the doubling formula)
    and P == -Q (H == 0 forces Z3 = 0, the correct infinity).
    """
    X1, Y1, Z1 = P
    X2, Y2, Z2 = Q
    p = ctx.p
    z1z1 = mont_sqr(Z1, p)
    z2z2 = mont_sqr(Z2, p)
    u1 = mont_mul(X1, z2z2, p)
    u2 = mont_mul(X2, z1z1, p)
    s1 = mont_mul(mont_mul(Y1, Z2, p), z2z2, p)
    s2 = mont_mul(mont_mul(Y2, Z1, p), z1z1, p)
    h = sub_mod(u2, u1, p)
    rr = sub_mod(s2, s1, p)
    h2 = add_mod(h, h, p)
    i = mont_sqr(h2, p)
    j = mont_mul(h, i, p)
    r2 = add_mod(rr, rr, p)
    v = mont_mul(u1, i, p)
    x3 = sub_mod(sub_mod(mont_sqr(r2, p), j, p), add_mod(v, v, p), p)
    s1j = mont_mul(s1, j, p)
    y3 = sub_mod(mont_mul(r2, sub_mod(v, x3, p), p), add_mod(s1j, s1j, p), p)
    z3 = mont_mul(
        sub_mod(sub_mod(mont_sqr(add_mod(Z1, Z2, p), p), z1z1, p), z2z2, p), h, p
    )
    inf1 = is_zero(Z1)
    inf2 = is_zero(Z2)
    same = is_zero(h) & is_zero(rr) & ~inf1 & ~inf2
    dx, dy, dz = jac_double(P, ctx)
    x = select(inf1, X2, select(inf2, X1, select(same, dx, x3)))
    y = select(inf1, Y2, select(inf2, Y1, select(same, dy, y3)))
    z = select(inf1, Z2, select(inf2, Z1, select(same, dz, z3)))
    return x, y, z


def jac_infinity(like: jax.Array):
    """Point at infinity broadcast over the batch dims of `like` [..., 16]."""
    z = jnp.zeros_like(like)
    return z, z, z


@partial(jax.jit, static_argnames="ctx")
def jac_to_affine(P, ctx: CurveCtx):
    """(X, Y, Z) -> (x, y, inf_mask); affine coords stay in Montgomery domain.

    Infinity lanes get x = y = 0 (mont_inv(0) == 0)."""
    X, Y, Z = P
    zinv = mont_inv(Z, ctx.p)
    zi2 = mont_sqr(zinv, ctx.p)
    zi3 = mont_mul(zi2, zinv, ctx.p)
    return mont_mul(X, zi2, ctx.p), mont_mul(Y, zi3, ctx.p), is_zero(Z)


def on_curve_mont(x_m: jax.Array, y_m: jax.Array, ctx: CurveCtx) -> jax.Array:
    """y^2 == x^3 + a*x + b (all Montgomery domain) -> bool[...]."""
    p = ctx.p
    rhs = mont_mul(mont_sqr(x_m, p), x_m, p)
    if not ctx.a_is_zero:
        rhs = add_mod(rhs, mont_mul(_const(ctx.a_m, x_m), x_m, p), p)
    rhs = add_mod(rhs, _const(ctx.b_m, x_m), p)
    return eq(mont_sqr(y_m, p), rhs)


def sqrt_mont(a_m: jax.Array, ctx: CurveCtx) -> jax.Array:
    """Square root mod p for p ≡ 3 (mod 4): a^((p+1)/4). Montgomery domain.

    Caller must check mont_sqr(result) == a to detect non-residues."""
    assert ctx.curve.p % 4 == 3
    return mont_pow(a_m, (ctx.curve.p + 1) // 4, ctx.p)


# ---------------------------------------------------------------------------
# Scalar bit plumbing and scalar-field (mod n) helpers
# ---------------------------------------------------------------------------


def scalar_bits_msb(k: jax.Array) -> jax.Array:
    """[..., 16] plain limbs -> [256, ...] bits, most significant first."""
    shifts = jnp.arange(16, dtype=jnp.uint32)
    bits = (k[..., :, None] >> shifts) & jnp.uint32(1)  # [..., limb, bit] LSB-first
    bits = bits.reshape(k.shape[:-1] + (256,))[..., ::-1]
    return jnp.moveaxis(bits, -1, 0)


def reduce_once(z: jax.Array, mod: Modulus) -> jax.Array:
    """z mod m for z < 2m (single conditional subtract).

    Valid for hash values vs. both curve orders: n > 2^255 for secp256k1 and
    SM2, so any 256-bit z satisfies z < 2n; likewise x < p < 2n."""
    d, borrow = _sub_with_borrow(z, _const(mod.limbs, z))
    return jnp.where((borrow == 0)[..., None], d, z)


def inv_mod(a: jax.Array, mod: Modulus) -> jax.Array:
    """a^-1 mod m for plain-domain a (0 -> 0). Fermat, batch-parallel."""
    return from_mont(mont_inv(to_mont(a, mod), mod), mod)


def mulmod(a: jax.Array, b: jax.Array, mod: Modulus) -> jax.Array:
    """a*b mod m for plain-domain a, b: mont_mul(aR, b) = a*b."""
    return mont_mul(to_mont(a, mod), b, mod)


def negmod(a: jax.Array, mod: Modulus) -> jax.Array:
    """(-a) mod m for plain-domain a < m."""
    return sub_mod(jnp.zeros_like(a), a, mod)


def lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """a < b over normalized limbs."""
    return ~geq(a, b)


def valid_scalar(x: jax.Array, ctx: CurveCtx) -> jax.Array:
    """1 <= x < n (signature component range check, both curves)."""
    n = _const(ctx.n.limbs, x)
    return ~is_zero(x) & lt(x, n)


# ---------------------------------------------------------------------------
# Scalar multiplication
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames="ctx")
def shamir_double_mul(k1, P1, k2, P2, ctx: CurveCtx):
    """k1*P1 + k2*P2 with one shared doubling chain (Shamir's trick).

    k1, k2: [..., 16] plain-domain scalars; P1, P2: (x_m, y_m) affine points in
    Montgomery domain (must not be infinity — guaranteed for curve points and
    the generator). Returns a Jacobian point; infinity encoded as Z == 0.
    This is the replica-side analog of the reference's per-tx `ECDSA_verify`
    inner loop — 256 iterations, identical schedule on every lane.
    """
    one = _const(ctx.p.r1, k1)
    j1 = (P1[0], P1[1], one)
    j2 = (P2[0], P2[1], one)
    j12 = jac_add(j1, j2, ctx)
    bits = (scalar_bits_msb(k1), scalar_bits_msb(k2))
    acc0 = jac_infinity(k1)

    def step(acc, bb):
        b1, b2 = bb
        acc = jac_double(acc, ctx)
        w1 = (b1 != 0) & (b2 == 0)
        w3 = (b1 != 0) & (b2 != 0)
        ax = select(w3, j12[0], select(w1, j1[0], j2[0]))
        ay = select(w3, j12[1], select(w1, j1[1], j2[1]))
        az = select(w3, j12[2], select(w1, j1[2], j2[2]))
        cx, cy, cz = jac_add(acc, (ax, ay, az), ctx)
        do = (b1 != 0) | (b2 != 0)
        return (
            select(do, cx, acc[0]),
            select(do, cy, acc[1]),
            select(do, cz, acc[2]),
        ), None

    acc, _ = lax.scan(step, acc0, bits)
    return acc


@partial(jax.jit, static_argnames="ctx")
def scalar_mul(k, P, ctx: CurveCtx):
    """k*P for affine Montgomery-domain P: plain double-and-add ladder."""
    one = _const(ctx.p.r1, k)
    jp = (P[0], P[1], one)
    acc0 = jac_infinity(k)

    def step(acc, b):
        acc = jac_double(acc, ctx)
        cx, cy, cz = jac_add(acc, jp, ctx)
        do = b != 0
        return (
            select(do, cx, acc[0]),
            select(do, cy, acc[1]),
            select(do, cz, acc[2]),
        ), None

    acc, _ = lax.scan(step, acc0, scalar_bits_msb(k))
    return acc


def generator(ctx: CurveCtx, like: jax.Array):
    """The curve generator broadcast across the batch dims of `like`."""
    return _const(ctx.gx_m, like), _const(ctx.gy_m, like)
