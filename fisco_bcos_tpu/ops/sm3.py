"""Batch SM3 (GB/T 32905) on TPU — the 国密 hash for sm_crypto chains.

Reference counterpart: bcos-crypto hash/SM3.h (OpenSSL-tassl EVP), hot in tx
hashing, state roots and merkle when the chain runs SM2/SM3 suites.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hash_common import digest_words_to_bytes_be, pad_md64

_IV = np.array(
    [0x7380166F, 0x4914B2B9, 0x172442D7, 0xDA8A0600,
     0xA96F30BC, 0x163138AA, 0xE38DEE4D, 0xB0FB0E4E],
    dtype=np.uint32,
)

def _rotl_int(v: int, n: int) -> int:
    n %= 32
    return ((v << n) | (v >> (32 - n))) & 0xFFFFFFFF


# Tj <<< j precomputed for the 64 rounds
_TJ = np.array(
    [_rotl_int(0x79CC4519 if j < 16 else 0x7A879D8A, j) for j in range(64)],
    dtype=np.uint32,
)


def _rotl(x, n: int):
    n %= 32
    if n == 0:
        return x
    return (x << n) | (x >> (32 - n))


def _p0(x):
    return x ^ _rotl(x, 9) ^ _rotl(x, 17)


def _p1(x):
    return x ^ _rotl(x, 15) ^ _rotl(x, 23)


def _schedule(block):
    """block [B, 16] -> (W [68, B], W1 [64, B]), unrolled over per-word
    [B] vectors (batch in the VPU minor axis; the scanned [B, 16] window
    version paid a minor-axis concat relayout per step)."""
    words = [block[:, i] for i in range(16)]
    for t in range(52):
        words.append(
            _p1(words[t] ^ words[t + 7] ^ _rotl(words[t + 13], 15))
            ^ _rotl(words[t + 3], 7)
            ^ words[t + 10]
        )
    w = jnp.stack(words, axis=0)  # [68, B]
    w1 = w[:64] ^ w[4:68]
    return w, w1


def _compress(state, block):
    """state [B, 8], block [B, 16] -> new state [B, 8]."""
    w, w1 = _schedule(block)

    def rnd(carry, xs):
        a, b, c, d, e, f, g, h = carry
        tj, wt, w1t, j16 = xs
        a12 = _rotl(a, 12)
        ss1 = _rotl(a12 + e + tj, 7)
        ss2 = ss1 ^ a12
        ff_lin = a ^ b ^ c
        ff_maj = (a & b) | (a & c) | (b & c)
        gg_lin = e ^ f ^ g
        gg_ch = (e & f) | (~e & g)
        ff = jnp.where(j16, ff_maj, ff_lin)
        gg = jnp.where(j16, gg_ch, gg_lin)
        tt1 = ff + d + ss2 + w1t
        tt2 = gg + h + ss1 + wt
        return (tt1, a, _rotl(b, 9), c, _p0(tt2), e, _rotl(f, 19), g), None

    init = tuple(state[:, i] for i in range(8))
    j16 = np.arange(64) >= 16
    out, _ = lax.scan(rnd, init, (jnp.asarray(_TJ), w[:64], w1, jnp.asarray(j16)))
    return state ^ jnp.stack(out, axis=1)


@jax.jit
def sm3_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """blocks [B, M, 16] uint32 BE words, nblocks [B] -> digests [B, 8] uint32."""
    bsz, m_max, _ = blocks.shape
    state0 = jnp.broadcast_to(jnp.asarray(_IV), (bsz, 8))

    def absorb(state, xs):
        blk, idx = xs
        new = _compress(state, blk)
        return jnp.where((idx < nblocks)[:, None], new, state), None

    state, _ = lax.scan(
        absorb,
        state0,
        (jnp.moveaxis(blocks, 1, 0), jnp.arange(m_max, dtype=jnp.int32)),
    )
    return state


def sm3_batch(msgs) -> np.ndarray:
    """Host convenience: list of bytes -> [B, 32] uint8 digests (device batch)."""
    from ..observability.device import device_span

    # the default shape key is the batch bucket — it approximates the
    # compiled program (the message-block dim also shapes it, so compile
    # counts are a lower bound)
    with device_span("sm3", len(msgs)):
        return sm3_batch_async(msgs)()


def sm3_batch_async(msgs):
    """Dispatch the device batch and defer the sync: returns a resolver
    () -> [B, 32] uint8. Lets callers queue several hash programs (tx
    root, receipts root, state root) before paying any device round
    trip."""
    n = len(msgs)
    blocks, nblocks = pad_md64(msgs)  # batch dim bucketed; slice below
    words = sm3_blocks(jnp.asarray(blocks), jnp.asarray(nblocks))
    # analysis: allow(host-sync, deferred resolver — the sync happens when
    # the caller RESOLVES the plane future, not at dispatch)
    return lambda: digest_words_to_bytes_be(np.asarray(words))[:n]


# -- progaudit shape spec (analysis/progaudit: canonical audited bucket) -----
PROGSPEC = {
    "sm3_blocks": {
        "bucket": 256,
        "inputs": lambda b: [((b, 1, 16), "uint32"), ((b,), "int32")],
    },
}
