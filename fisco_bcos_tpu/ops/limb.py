"""Limb-major 256-bit modular arithmetic — the TPU-native bignum core.

Replaces the reference's CPU bignum (wedpr-crypto Rust FFI / OpenSSL BN behind
bcos-crypto's secp256k1/SM2 paths) with a formulation shaped for the TPU VPU:

- A 256-bit number is 16 little-endian 16-bit limbs in a uint32 array of
  shape ``[L, T]`` — **limb-major**: the minor (lane) axis is the batch, so
  every elementwise op runs at full 128-lane VPU utilization. (The round-1
  layout ``[B, 16]`` put the 16-limb axis in the lanes — 12.5% utilization —
  and was the single biggest cost of the 1.36× bench result.)
- Multiplication is 16 unrolled rows of vector MACs with 16-bit lo/hi
  splitting (every partial product and column sum stays inside uint32);
  there are no matmuls — int32 matmul does not map to the MXU.
- Carry propagation is Kogge–Stone over the limb axis
  (``lax.associative_scan``, log₂ depth), never a sequential scan.
- Modular reduction is **pseudo-Mersenne folding** (``FoldField``) for
  moduli of the form 2^256 − c with small c — secp256k1's p and n both
  qualify — and word Montgomery (``MontField``) for arbitrary odd moduli
  (SM2). Both present the same field-ops protocol so the EC layer in
  :mod:`fisco_bcos_tpu.ops.ec` is generic over them.

Everything here is plain ``jnp`` on values — the same functions run inside a
Pallas TPU kernel (VMEM-resident, the fast path) and under ordinary XLA on
CPU (the portable/correctness path); integer semantics make the two
bit-identical by construction, which is what consensus code requires.

Host-side byte/int conversions stay in :mod:`fisco_bcos_tpu.ops.bigint`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

LIMBS = 16
LIMB_BITS = 16
# numpy scalar, not jnp: a module-level jax.Array would be a captured
# constant inside Pallas kernel traces (Mosaic rejects those); np scalars
# stay jaxpr literals.
_MASK = np.uint32(0xFFFF)
_R = 1 << 256


def int_to_rows(x: int, width: int = LIMBS) -> np.ndarray:
    """Python int -> [width] uint32 little-endian 16-bit limbs."""
    if not 0 <= x < 1 << (LIMB_BITS * width):
        raise ValueError("int_to_rows: out of range")
    return np.array(
        [(x >> (LIMB_BITS * i)) & 0xFFFF for i in range(width)], dtype=np.uint32
    )


def rows_to_ints(a) -> list[int]:
    """[L, T] limbs -> list of T Python ints (host-side, for tests)."""
    a = np.asarray(a)
    return [
        sum(int(a[i, j]) << (LIMB_BITS * i) for i in range(a.shape[0]))
        for j in range(a.shape[1])
    ]


def dev_vec(arr, dtype=jnp.uint32) -> jax.Array:
    """1-D host constant -> device vector assembled from scalar constants.

    Pallas kernel bodies may not capture array constants (only scalars), so
    every host-side table/constant that flows into the shared field code is
    built this way; XLA constant-folds the stack outside Pallas."""
    return jnp.stack([jnp.array(int(v), dtype) for v in arr])


def const_rows(limbs_np: np.ndarray, t: int | jax.Array) -> jax.Array:
    """[L] host constant -> [L, T] broadcast (T from an int or a like-array).

    Plain XLA: one embedded constant + one broadcast. Mosaic trace: built
    from scalar literals (Pallas kernels may not capture array constants) —
    L fulls + a stack, which Mosaic constant-folds."""
    if not isinstance(t, int):
        t = t.shape[-1]
    if is_mosaic_trace():
        return jnp.stack([jnp.full((t,), int(v), jnp.uint32) for v in limbs_np])
    arr = np.asarray(limbs_np, dtype=np.uint32)
    return jnp.broadcast_to(jnp.asarray(arr)[:, None], (arr.shape[0], t))


# ---------------------------------------------------------------------------
# Carry machinery (Kogge–Stone along the limb axis = axis 0)
# ---------------------------------------------------------------------------


def _gp_combine(x, y):
    gx, px = x
    gy, py = y
    return gy | (py & gx), py & px


def _shift_up(x: jax.Array) -> jax.Array:
    """[L, T] -> [L, T] shifted one limb toward the high end (axis 0)."""
    return jnp.concatenate([jnp.zeros_like(x[:1]), x[:-1]], axis=0)


def row(x: jax.Array, i: int) -> jax.Array:
    """Static row i of [L, T] -> [T] via a static slice + squeeze.

    NEVER ``x[i]``: jnp integer indexing lowers through dynamic_slice even
    for constant indices, and Mosaic (Pallas TPU) has no dynamic_slice."""
    return jnp.squeeze(lax.slice_in_dim(x, i, i + 1, axis=0), axis=0)


def _carry_in(g: jax.Array, p: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-position carry/borrow-in from generate/propagate; also returns the
    final carry-out row (both bool [T]).

    Explicit Kogge–Stone doubling loop rather than ``lax.associative_scan``:
    the scan's recursive odd/even decomposition emits zero-length slices,
    which Mosaic (Pallas TPU) rejects as 0-sized vectors; this loop is the
    same log₂-depth circuit with every slice non-empty. Bits ride int32
    lanes, not bool — Mosaic cannot concatenate i1 (mask-register) vectors
    ("Invalid vector register cast")."""
    G = g.astype(jnp.int32)
    P = p.astype(jnp.int32)
    shift = 1
    n = g.shape[0]
    while shift < n:
        # segment ending at i-shift, shifted into position i; out-of-range
        # rows get the combine identity (g=0, p=1)
        Gs = jnp.concatenate([jnp.zeros_like(G[:shift]), G[:-shift]], axis=0)
        Ps = jnp.concatenate([jnp.ones_like(P[:shift]), P[:-shift]], axis=0)
        G, P = _gp_combine((Gs, Ps), (G, P))
        shift *= 2
    cin = jnp.concatenate([jnp.zeros_like(G[:1]), G[:-1]], axis=0)
    return cin != 0, row(G, n - 1) != 0


def carry_norm(cols: jax.Array) -> jax.Array:
    """Carry-propagate column sums: [L, T] uint32 (any uint32 value: the
    two-pass split bounds s = lo16 + prev_hi16 < 2^17 and t ≤ 2^16 before
    the Kogge–Stone increment pass, so no intermediate can overflow —
    mul_cols feeds columns < 2^22, mul_small up to ~2^31) ->
    [L+1, T] normalized 16-bit limbs (top row = final carry-out)."""
    cols = jnp.concatenate([cols, jnp.zeros_like(cols[:1])], axis=0)
    s = (cols & _MASK) + _shift_up(cols >> LIMB_BITS)  # < 2^16 + 2^11
    t = (s & _MASK) + _shift_up(s >> LIMB_BITS)  # ≤ 2^16; increments {0,1}
    g = t > _MASK
    p = t == _MASK
    cin, _ = _carry_in(g, p)
    return (t + cin.astype(jnp.uint32)) & _MASK


def sub_borrow(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(a - b) limbwise over axis 0 -> (diff [L, T], borrow_out bool [T])."""
    g = a < b
    p = a == b
    bin_, bout = _carry_in(g, p)
    diff = (a + jnp.uint32(0x10000) - b - bin_.astype(jnp.uint32)) & _MASK
    return diff, bout


def _or_fold(x: jax.Array) -> jax.Array:
    """Bitwise-OR all rows of [L, T] -> [T] via a log-depth halving tree
    (no jnp.all/jnp.any: Mosaic lacks those reductions for integer input,
    and this shape serves both backends identically)."""
    while x.shape[0] > 1:
        half = x.shape[0] // 2
        rest = x[2 * half :]  # odd leftover row, if any
        x = x[:half] | x[half : 2 * half]
        if rest.shape[0]:
            x = jnp.concatenate([x[:1] | rest, x[1:]], axis=0)
    return row(x, 0)


def is_zero(a: jax.Array) -> jax.Array:
    return _or_fold(a) == 0


def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return _or_fold(a ^ b) == 0


def geq(a: jax.Array, b: jax.Array) -> jax.Array:
    _, borrow = sub_borrow(a, b)
    return ~borrow


def lt(a: jax.Array, b: jax.Array) -> jax.Array:
    _, borrow = sub_borrow(a, b)
    return borrow


def select(cond: jax.Array, a, b):
    """cond [T] -> cond ? a : b over [..., T] operands (or tuples of them)."""
    if isinstance(a, tuple):
        return tuple(select(cond, x, y) for x, y in zip(a, b))
    shape = (1,) * (a.ndim - 1) + cond.shape
    return jnp.where(cond.reshape(shape), a, b)


# ---------------------------------------------------------------------------
# Multiplication (unrolled row MACs with 16-bit splitting; no matmuls)
# ---------------------------------------------------------------------------


def _placed(x: jax.Array, offset: int, out: int) -> jax.Array:
    """[n, T] rows placed at row `offset` of an [out, T] zero canvas —
    zeros‖x‖zeros concat (2 broadcasts + 1 concat). NEVER `.at[...].add`:
    a static-slice scatter is the single most expensive op for XLA to
    compile (round-2 lesson: ~11k scatters made one EC program a >10-minute
    CPU compile), and Mosaic cannot lower scatter at all."""
    n = min(x.shape[0], out - offset)
    if n <= 0:
        return jnp.zeros((out, x.shape[1]), x.dtype)
    parts = []
    if offset:
        parts.append(jnp.zeros((offset, x.shape[1]), x.dtype))
    parts.append(x[:n])
    if offset + n < out:
        parts.append(jnp.zeros((out - offset - n, x.shape[1]), x.dtype))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def _add_rows(x: jax.Array) -> jax.Array:
    """Sum the rows of [L, T] -> [1, T] via a log-depth halving tree (no
    jnp.sum: Mosaic lacks integer reductions). Caller bounds the values so
    sums cannot overflow uint32."""
    while x.shape[0] > 1:
        half = x.shape[0] // 2
        rest = x[2 * half :]
        x = x[:half] + x[half : 2 * half]
        if rest.shape[0]:
            x = jnp.concatenate([x[:1] + rest, x[1:]], axis=0)
    return x


def _sum_terms(terms: list[jax.Array]) -> jax.Array:
    """Balanced tree-add of equal-shape u32 arrays.

    Mosaic has no unsigned reductions, so no stack+jnp.sum; a log-depth add
    tree is equally fusable under XLA and trivially lowerable under Mosaic."""
    while len(terms) > 1:
        nxt = [
            terms[i] + terms[i + 1] if i + 1 < len(terms) else terms[i]
            for i in range(0, len(terms), 2)
        ]
        terms = nxt
    return terms[0]


def mul_cols(a: jax.Array, b: jax.Array, out: int = 2 * LIMBS) -> jax.Array:
    """Column sums of a*b: [16, T] x [16, T] -> [out, T] raw columns.

    Column k collects lo16(a_i*b_j) for i+j == k and hi16 for i+j == k-1;
    every column sum is < 32 * 2^16 < 2^22, inside uint32. The 32 shifted
    row groups are summed with one stacked reduction (scatter-free).
    """
    terms = []
    for i in range(LIMBS):
        # static slice, not a[i]: integer indexing lowers via dynamic_slice
        prod = lax.slice_in_dim(a, i, i + 1, axis=0) * b  # [16, T], < 2^32
        terms.append(_placed(prod & _MASK, i, out))
        terms.append(_placed(prod >> LIMB_BITS, i + 1, out))
    return _sum_terms(terms)


def sqr_cols(a: jax.Array, out: int = 2 * LIMBS) -> jax.Array:
    """Column sums of a*a exploiting symmetry: the off-diagonal partial
    products a_i*a_j (i < j) are computed once and doubled, and all 16
    diagonal products come from ONE elementwise multiply — 136 partial-
    product rows instead of :func:`mul_cols`'s 256, with the same 32-term
    add tree. Doubling happens after the lo/hi split (terms < 2^17), so
    column sums stay < 32 * 2^17 < 2^23, inside carry_norm's budget."""
    t = a.shape[1]
    d = a * a  # [16, T] diagonal products a_i^2, column 2i
    zero = jnp.zeros((LIMBS, 1, t), jnp.uint32)
    # interleave rows with zeros: (d0, 0, d1, 0, ...) -> columns 0,2,4,...
    d_lo = jnp.concatenate(
        [(d & _MASK)[:, None], zero], axis=1
    ).reshape(2 * LIMBS, t)
    # (0, h0, 0, h1, ...) -> columns 1,3,5,...
    d_hi = jnp.concatenate(
        [zero, (d >> LIMB_BITS)[:, None]], axis=1
    ).reshape(2 * LIMBS, t)
    terms = [_placed(d_lo, 0, out), _placed(d_hi, 0, out)]
    for i in range(LIMBS - 1):
        ai = lax.slice_in_dim(a, i, i + 1, axis=0)  # [1, T]
        rest = lax.slice_in_dim(a, i + 1, LIMBS, axis=0)  # [15-i, T]
        prod = ai * rest  # rows j = i+1..15, value a_i*a_j < 2^32
        terms.append(_placed(((prod & _MASK) << 1), 2 * i + 1, out))
        terms.append(_placed(((prod >> LIMB_BITS) << 1), 2 * i + 2, out))
    return _sum_terms(terms)


def mul_const_cols(
    hi: jax.Array, c_limbs: np.ndarray, out: int
) -> jax.Array:
    """Column sums of hi * c for a small host constant c: [H, T] x [C] ->
    [out, T] raw columns (same lo/hi splitting as :func:`mul_cols`)."""
    terms = [jnp.zeros((out, hi.shape[1]), jnp.uint32)]
    for k, cval in enumerate(np.asarray(c_limbs, dtype=np.uint64)):
        cval = int(cval)
        if cval == 0:
            continue
        prod = hi * np.uint32(cval)  # < 2^32
        terms.append(_placed(prod & _MASK, k, out))
        terms.append(_placed(prod >> LIMB_BITS, k + 1, out))
    return _sum_terms(terms)


def add_widen(a: jax.Array, b: jax.Array) -> jax.Array:
    """Exact add of two normalized arrays (equal or different widths) ->
    [max(L)+1, T] normalized."""
    w = max(a.shape[0], b.shape[0])
    t = a.shape[1]

    def pad(x):
        if x.shape[0] == w:
            return x
        return jnp.concatenate(
            [x, jnp.zeros((w - x.shape[0], t), jnp.uint32)], axis=0
        )

    return carry_norm(pad(a) + pad(b))


def cond_sub(x: jax.Array, m_limbs: np.ndarray) -> jax.Array:
    """x - m if x >= m else x, for normalized x < 2m. Returns [16, T]."""
    w = x.shape[0]
    m_pad = np.zeros(w, dtype=np.uint32)
    m_pad[: LIMBS] = m_limbs
    mc = const_rows(m_pad, x)
    diff, borrow = sub_borrow(x, mc)
    return select(~borrow, diff, x)[:LIMBS]


# ---------------------------------------------------------------------------
# Field protocols
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FoldField:
    """GF(m) for pseudo-Mersenne m = 2^256 - c (c ≤ ~2^130): plain-domain
    values, reduction by folding hi*c back into the low words.

    secp256k1's p (c = 2^32 + 977) and n (c ≈ 1.27*2^128) both qualify —
    this is the fast path for the north-star kernel, replacing the generic
    Montgomery REDC of round 1 (3 wide products per mul) with one wide
    product plus cheap constant folds.
    """

    m_int: int
    c_limbs: np.ndarray = field(repr=False)
    m_limbs: np.ndarray = field(repr=False)

    def __hash__(self):
        return hash(("fold", self.m_int))

    def __eq__(self, other):
        return isinstance(other, FoldField) and other.m_int == self.m_int

    # -- domain conversions (plain domain: all identity) --
    def enc(self, v: int) -> np.ndarray:
        return int_to_rows(v % self.m_int)

    def from_plain(self, x: jax.Array) -> jax.Array:
        return x

    def to_plain(self, x: jax.Array) -> jax.Array:
        return x

    def one(self, t) -> jax.Array:
        return const_rows(int_to_rows(1), t)

    # -- reduction --
    def reduce_wide(self, x: jax.Array, bound: int) -> jax.Array:
        """x (normalized limbs, value < bound, bound exclusive) -> x mod m.

        Folds value = lo + hi*2^256 ≡ lo + hi*c (mod m) until the static
        value bound drops below 2m, then one conditional subtract. Any
        contribution the static column clamp drops is provably zero (a
        nonzero write at column k implies value ≥ 2^(16k) > bound).
        """
        c_int = _R - self.m_int
        while bound > 2 * self.m_int:
            lo, hi = x[:LIMBS], x[LIMBS:]
            if hi.shape[0] == 0:
                break
            hi_max = (bound - 1) >> 256
            bound = (_R - 1) + hi_max * c_int + 1
            width = max((bound - 1).bit_length() + 15, 17 * 16) // 16
            cols = mul_const_cols(hi, self.c_limbs, width)
            cols = cols + _placed(lo, 0, width)
            x = carry_norm(cols)[:width]
        return cond_sub(x, self.m_limbs)

    def reduce1(self, x: jax.Array) -> jax.Array:
        """x < 2m (16 limbs) -> x mod m (one conditional subtract)."""
        return cond_sub(x, self.m_limbs)

    # -- field ops --
    def mul(self, a: jax.Array, b: jax.Array) -> jax.Array:
        wide = carry_norm(mul_cols(a, b))[: 2 * LIMBS]
        return self.reduce_wide(wide, (_R - 1) ** 2 + 1)

    def sqr(self, a: jax.Array) -> jax.Array:
        wide = carry_norm(sqr_cols(a))[: 2 * LIMBS]
        return self.reduce_wide(wide, (_R - 1) ** 2 + 1)

    def mul_small(self, a: jax.Array, c: int) -> jax.Array:
        """a * c for a small host constant c < 2^15 — one scalar-broadcast
        multiply + carry + fold (~1/10 of a full mul). The RCB complete
        group law multiplies by 3b per add; for secp256k1 b3 = 21."""
        if not 0 < c < 1 << 15:
            raise ValueError("mul_small needs 0 < c < 2^15")
        cols = a * np.uint32(c)  # limbs < 2^16 * 2^15 = 2^31: no overflow
        wide = carry_norm(cols)[: LIMBS + 1]
        return self.reduce_wide(wide, (_R - 1) * c + 1)

    def add(self, a: jax.Array, b: jax.Array) -> jax.Array:
        return cond_sub(add_widen(a, b), self.m_limbs)

    def sub(self, a: jax.Array, b: jax.Array) -> jax.Array:
        diff, borrow = sub_borrow(a, b)
        plus = add_widen(diff, const_rows(self.m_limbs, a))[:LIMBS]
        return select(borrow, plus, diff)

    def neg(self, a: jax.Array) -> jax.Array:
        return self.sub(jnp.zeros_like(a), a)

    def inv(self, a: jax.Array) -> jax.Array:
        """a^-1 mod m for prime m (Fermat); 0 -> 0."""
        return pow_static(self, a, self.m_int - 2)

    def sqrt(self, a: jax.Array) -> jax.Array:
        """Square root candidate for m ≡ 3 (mod 4): a^((m+1)/4). Caller must
        check sqr(result) == a to detect non-residues."""
        assert self.m_int % 4 == 3
        return pow_static(self, a, (self.m_int + 1) // 4)


def make_fold_field(m: int) -> FoldField:
    c = _R - m
    if not 0 < c < 1 << 132:
        raise ValueError("FoldField needs m = 2^256 - c with small c")
    nc = (c.bit_length() + 15) // 16
    return FoldField(
        m_int=m, c_limbs=int_to_rows(c, nc), m_limbs=int_to_rows(m)
    )


@dataclass(frozen=True)
class SparseFoldField(FoldField):
    """GF(m) for Solinas m where 2^256 - m = Σ 2^(16·o) − Σ 2^(16·o') —
    the complement is a signed sum of limb-aligned powers, so the fold
    hi·c is pure shifted adds/subs with NO multiplies at all. SM2's prime
    qualifies (2^256 − p = 2^224 + 2^96 − 2^64 + 1): this replaces the
    generic Montgomery REDC (~2.5 wide products per mul) with one wide
    product, one dense per-limb table fold and one signed shift-add round,
    and makes the domain conversions identity. Everything except
    :meth:`reduce_wide` is inherited from the plain-domain
    :class:`FoldField`."""

    pos_offsets: tuple[int, ...] = ()  # limb offsets o with +2^(16o)
    neg_offsets: tuple[int, ...] = ()
    # [16, 16] uint32: row k = limbs of 2^(256+16k) mod m (dense fold table)
    fold_rows: np.ndarray = field(default=None, repr=False)

    def __hash__(self):
        return hash(("sparsefold", self.m_int))

    def __eq__(self, other):
        return isinstance(other, SparseFoldField) and other.m_int == self.m_int

    @property
    def _c_pos(self) -> int:
        return sum(1 << (16 * o) for o in self.pos_offsets)

    def _table_fold(self, lo: jax.Array, hi: jax.Array) -> tuple[jax.Array, int]:
        """lo [16,T] + hi [H≤16,T] -> normalized limbs of
        lo + Σ_k hi_k · (2^(256+16k) mod m), with its exclusive bound.

        One output column j sums h_k·T[k][j] over k: a single broadcast
        multiply per column plus a log-tree row sum (≤16 terms of < 2^16
        after the lo/hi split, so sums stay < 2^20 — far inside uint32)."""
        h = hi.shape[0]
        tab = self.fold_rows[:h]  # [h, 16]
        width = 18  # value < 2^256 + 16·2^16·m < 2^277
        terms = [_placed(lo, 0, width)]
        for j in range(LIMBS):
            tj = dev_vec(tab[:, j]).reshape(h, 1)  # column constants
            prod = hi * tj  # [h, T], products < 2^32
            terms.append(_placed(_add_rows(prod & _MASK), j, width))
            terms.append(_placed(_add_rows(prod >> LIMB_BITS), j + 1, width))
        bound = _R + (LIMBS * ((1 << LIMB_BITS) - 1)) * self.m_int
        return carry_norm(_sum_terms(terms))[:width], bound

    def reduce_wide(self, x: jax.Array, bound: int) -> jax.Array:
        """x (normalized limbs, value < bound) -> x mod m.

        Wide inputs (a full product) take ONE dense table fold
        (lo + Σ hi_k·(2^(256+16k) mod m)), leaving a ~2^21 hi that a single
        signed shift-add round (value = lo + Σ(hi<<16o) − Σ(hi<<16o'),
        which cannot go negative) folds under 2m. Narrow inputs skip
        straight to shift-add rounds."""
        c_pos = self._c_pos
        if x.shape[0] > LIMBS + 2 and bound > 2 * self.m_int:
            x, bound = self._table_fold(x[:LIMBS], x[LIMBS:])
        while bound > 2 * self.m_int:
            lo, hi = x[:LIMBS], x[LIMBS:]
            if hi.shape[0] == 0:
                break
            hi_max = (bound - 1) >> 256
            bound = (_R - 1) + hi_max * c_pos + 1
            width = max((bound - 1).bit_length() + 15 + 16, 17 * 16) // 16
            cols = _placed(lo, 0, width)
            for o in self.pos_offsets:
                cols = cols + _placed(hi, o, width)
            pos_n = carry_norm(cols)[:width]
            neg_cols = _placed(hi, self.neg_offsets[0], width)
            for o in self.neg_offsets[1:]:
                neg_cols = neg_cols + _placed(hi, o, width)
            neg_n = carry_norm(neg_cols)[:width]
            diff, _borrow = sub_borrow(pos_n, neg_n)  # value ≥ 0: no borrow
            x = diff
        return cond_sub(x, self.m_limbs)


# Solinas decompositions of 2^256 − m into ±2^(16·o) terms, per modulus
_SPARSE_COMPLEMENTS: dict[int, tuple[tuple[int, ...], tuple[int, ...]]] = {
    # SM2 p: 2^256 − p = 2^224 + 2^96 − 2^64 + 1
    0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF00000000FFFFFFFFFFFFFFFF: (
        (14, 6, 0),
        (4,),
    ),
}


def make_sparse_fold_field(m: int) -> SparseFoldField:
    pos, neg = _SPARSE_COMPLEMENTS[m]
    c = _R - m
    assert sum(1 << (16 * o) for o in pos) - sum(1 << (16 * o) for o in neg) == c
    return SparseFoldField(
        m_int=m,
        # c_limbs is only read by FoldField.reduce_wide, which is overridden
        c_limbs=int_to_rows(c, (c.bit_length() + 15) // 16),
        m_limbs=int_to_rows(m),
        pos_offsets=pos,
        neg_offsets=neg,
        fold_rows=np.stack(
            [int_to_rows(pow(2, 256 + 16 * k, m)) for k in range(LIMBS)]
        ),
    )


@dataclass(frozen=True)
class MontField:
    """GF(m) for arbitrary odd m < 2^256: Montgomery-domain values (x·R mod m,
    R = 2^256), word REDC reduction. The generic path (SM2's p and n)."""

    m_int: int
    m_limbs: np.ndarray = field(repr=False)
    mprime: np.ndarray = field(repr=False)  # -m^-1 mod 2^256
    r1: np.ndarray = field(repr=False)  # R mod m (the field's 1)
    r2: np.ndarray = field(repr=False)  # R^2 mod m

    def __hash__(self):
        return hash(("mont", self.m_int))

    def __eq__(self, other):
        return isinstance(other, MontField) and other.m_int == self.m_int

    def enc(self, v: int) -> np.ndarray:
        return int_to_rows((v % self.m_int) * _R % self.m_int)

    def one(self, t) -> jax.Array:
        return const_rows(self.r1, t)

    def redc(self, t: jax.Array) -> jax.Array:
        """t [32, T] (t < m*R) -> t*R^-1 mod m, [16, T]."""
        m_val = carry_norm(
            mul_cols(t[:LIMBS], const_rows(self.mprime, t), out=LIMBS)
        )[:LIMBS]
        mm = carry_norm(mul_cols(m_val, const_rows(self.m_limbs, t)))[
            : 2 * LIMBS
        ]
        s = add_widen(t, mm)  # [33, T]; low 16 limbs are zero
        return cond_sub(s[LIMBS:], self.m_limbs)

    def from_plain(self, x: jax.Array) -> jax.Array:
        return self.mul(x, const_rows(self.r2, x))

    def to_plain(self, x: jax.Array) -> jax.Array:
        pad = jnp.zeros((LIMBS, x.shape[1]), jnp.uint32)
        return self.redc(jnp.concatenate([x, pad], axis=0))

    def mul(self, a: jax.Array, b: jax.Array) -> jax.Array:
        return self.redc(carry_norm(mul_cols(a, b))[: 2 * LIMBS])

    def sqr(self, a: jax.Array) -> jax.Array:
        return self.redc(carry_norm(sqr_cols(a))[: 2 * LIMBS])

    def mul_small(self, a: jax.Array, c: int) -> jax.Array:
        """a * c for tiny c via an addition chain (scaling commutes with the
        Montgomery representation; each step is one conditional subtract,
        far cheaper than a REDC mul). Used by the complete group law's
        a = -3 path (c = 3)."""
        if not 0 < c < 32:
            raise ValueError("MontField.mul_small supports 0 < c < 32")
        # double-and-add on the bits of c, msb first
        acc = None
        for bit in bin(c)[2:]:
            if acc is not None:
                acc = self.add(acc, acc)
            if bit == "1":
                acc = a if acc is None else self.add(acc, a)
        return acc

    def add(self, a: jax.Array, b: jax.Array) -> jax.Array:
        return cond_sub(add_widen(a, b), self.m_limbs)

    def sub(self, a: jax.Array, b: jax.Array) -> jax.Array:
        diff, borrow = sub_borrow(a, b)
        plus = add_widen(diff, const_rows(self.m_limbs, a))[:LIMBS]
        return select(borrow, plus, diff)

    def neg(self, a: jax.Array) -> jax.Array:
        return self.sub(jnp.zeros_like(a), a)

    def inv(self, a: jax.Array) -> jax.Array:
        return pow_static(self, a, self.m_int - 2)

    def sqrt(self, a: jax.Array) -> jax.Array:
        assert self.m_int % 4 == 3
        return pow_static(self, a, (self.m_int + 1) // 4)


@lru_cache(maxsize=None)
def make_mont_field(m: int) -> MontField:
    if m % 2 == 0 or not 2 < m < _R:
        raise ValueError("modulus must be odd and < 2^256")
    return MontField(
        m_int=m,
        m_limbs=int_to_rows(m),
        mprime=int_to_rows((-pow(m, -1, _R)) % _R),
        r1=int_to_rows(_R % m),
        r2=int_to_rows(_R * _R % m),
    )


# ---------------------------------------------------------------------------
# Windowed exponentiation with a static exponent
# ---------------------------------------------------------------------------

_POW_W = 4


def _exp_windows(e: int) -> np.ndarray:
    """Static exponent -> MSB-first 4-bit windows (leading zeros stripped)."""
    if e <= 0:
        raise ValueError("pow_static needs a positive exponent")
    nw = (e.bit_length() + _POW_W - 1) // _POW_W
    return np.array(
        [(e >> (_POW_W * i)) & 0xF for i in range(nw - 1, -1, -1)],
        dtype=np.uint32,
    )


# When set, shared field/EC code traces in its Mosaic-safe shape (fori
# loops, masked where-chains, unrolled tables — no scan xs/ys, whose
# dynamic_slice/dynamic_update_slice lowering Pallas TPU lacks). Otherwise
# (plain XLA: CPU tests, virtual meshes, fallback), the same math traces as
# compact lax.scan programs — ~15x smaller HLO, which is the difference
# between seconds and tens of minutes of XLA-CPU compile on a 1-core host.
# Integer semantics are identical element-for-element, so both shapes are
# bit-identical in output — the consensus requirement.
# A ContextVar, not a module global: a Pallas kernel trace on one thread
# must not leak the Mosaic shape into a concurrent plain-XLA trace.
import contextvars as _contextvars

_MOSAIC_TRACE: _contextvars.ContextVar[bool] = _contextvars.ContextVar(
    "mosaic_trace", default=False
)


def is_mosaic_trace() -> bool:
    return _MOSAIC_TRACE.get()


class mosaic_trace:
    """Context manager scoping the Mosaic trace shape to this thread."""

    def __enter__(self):
        self._token = _MOSAIC_TRACE.set(True)

    def __exit__(self, *exc):
        _MOSAIC_TRACE.reset(self._token)


def static_lookup(vals: np.ndarray, i: jax.Array) -> jax.Array:
    """vals[i] for a static host table and a traced scalar index — a masked
    where-chain (no gather/dynamic_slice; Mosaic supports neither)."""
    out = jnp.full((), int(vals[0]), jnp.int32)
    for j in range(1, len(vals)):
        out = jnp.where(i == j, np.int32(int(vals[j])), out)
    return out


def pow_static(F, a: jax.Array, e: int) -> jax.Array:
    """a^e in field F for a fixed Python-int exponent.

    4-bit windows, MSB first: per window 4 squarings + one table multiply
    selected branch-free from the 15 precomputed powers; the loop/table
    shape follows :func:`is_mosaic_trace` (see its comment).
    """
    wins = _exp_windows(e)

    if is_mosaic_trace():
        # table[c-1] = a^c for c in 1..15 — 14 unrolled sequential muls
        tab = [a]
        for _ in range(14):
            tab.append(F.mul(tab[-1], a))
        first = int(wins[0])
        assert first != 0
        acc0 = tab[first - 1]
        if len(wins) == 1:
            return acc0
        rest = wins[1:]

        def body(i, acc):
            c = static_lookup(rest, i)
            for _ in range(_POW_W):
                acc = F.sqr(acc)
            sel = tab[0]
            for k in range(2, 16):
                sel = jnp.where(c == k, tab[k - 1], sel)
            with_mul = F.mul(acc, sel)
            return jnp.where(c == 0, acc, with_mul)

        return lax.fori_loop(0, len(rest), body, acc0)

    # compact scan shape (plain XLA)
    def _tab_step(prev, _):
        nxt = F.mul(prev, a)
        return nxt, nxt

    _, rest_tab = lax.scan(_tab_step, a, None, length=14)
    tab = jnp.concatenate([a[None], rest_tab], axis=0)  # [15, 16, T]

    first = int(wins[0])
    assert first != 0
    acc0 = tab[first - 1]
    if len(wins) == 1:
        return acc0

    def body(acc, c):
        for _ in range(_POW_W):
            acc = F.sqr(acc)
        sel = tab[0]
        for k in range(2, 16):
            sel = select(c == k, tab[k - 1], sel)
        with_mul = F.mul(acc, sel)
        return select(c == 0, acc, with_mul), None

    acc, _ = lax.scan(body, acc0, dev_vec(wins[1:]))
    return acc
