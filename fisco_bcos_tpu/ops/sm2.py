"""Batch SM2 (GB/T 32918.2) signature verification on TPU — 国密 suite.

Reference counterpart: bcos-crypto signature/sm2/SM2Crypto.cpp:29-91 (wedpr
FFI) and the OpenSSL-tassl FastSM2 path (signature/fastsm2/fast_sm2.cpp).
Signature format follows the reference: 64-byte r‖s with the 64-byte
uncompressed public key appended, and "recover" = parse-pubkey-then-verify
(SM2Crypto.cpp:81-91) — SM2 has no algebraic pubkey recovery in this scheme.

The digest is e = SM3(ZA ‖ M) with ZA = SM3(ENTL ‖ ID ‖ a ‖ b ‖ Gx ‖ Gy ‖
Px ‖ Py) and the default user id "1234567812345678"; both are fixed-length
messages, so e-derivation itself runs on the batch SM3 kernel.

Verification: t = (r + s) mod n (t ≠ 0); (x1, y1) = s*G + t*Q;
valid iff (e + x1) mod n == r.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.ref.ecdsa import SM2_DEFAULT_ID
from . import bigint
from .bigint import bytes_be_to_limbs, from_mont, is_zero, to_mont
from .hash_common import bucket_batch as _bucket
from .hash_common import pad_rows as _pad_rows
from .ec import (
    SM2_CTX,
    generator,
    jac_to_affine,
    lt,
    on_curve_mont,
    reduce_once,
    shamir_double_mul,
    valid_scalar,
)
from .sm3 import sm3_batch

_CTX = SM2_CTX


@jax.jit
def verify_device(e, r, s, qx, qy):
    """Batch SM2 verify. All inputs [..., 16] plain-domain limbs.

    e: SM3(ZA ‖ M) digest as an integer; (r, s): signature; (qx, qy): affine
    public key. Returns bool[...].
    """
    ctx = _CTX
    p_arr = bigint._const(ctx.p.limbs, qx)
    valid = valid_scalar(r, ctx) & valid_scalar(s, ctx)
    valid &= lt(qx, p_arr) & lt(qy, p_arr)
    qx_m = to_mont(qx, ctx.p)
    qy_m = to_mont(qy, ctx.p)
    valid &= on_curve_mont(qx_m, qy_m, ctx)
    t = bigint.add_mod(r, s, ctx.n)
    valid &= ~is_zero(t)
    P1 = shamir_double_mul(s, generator(ctx, qx), t, (qx_m, qy_m), ctx)
    x1_m, _, inf = jac_to_affine(P1, ctx)
    x1 = reduce_once(from_mont(x1_m, ctx.p), ctx.n)
    e_n = reduce_once(e, ctx.n)
    R = bigint.add_mod(e_n, x1, ctx.n)
    return valid & ~inf & bigint.eq(R, r)


# ---------------------------------------------------------------------------
# Host wrappers
# ---------------------------------------------------------------------------


def sm2_e_batch(
    msg_hashes: np.ndarray, pubkeys: np.ndarray, user_id: bytes = SM2_DEFAULT_ID
) -> np.ndarray:
    """e = SM3(ZA ‖ M) for a batch: [B,32] hashes + [B,64] pubkeys -> [B,32].

    ZA inputs are fixed-length, so both SM3 passes run on the device kernel."""
    msg_hashes = np.asarray(msg_hashes, dtype=np.uint8)
    pubkeys = np.asarray(pubkeys, dtype=np.uint8)
    c = _CTX.curve
    entl = (len(user_id) * 8).to_bytes(2, "big")
    prefix = np.frombuffer(
        entl
        + user_id
        + c.a.to_bytes(32, "big")
        + c.b.to_bytes(32, "big")
        + c.gx.to_bytes(32, "big")
        + c.gy.to_bytes(32, "big"),
        dtype=np.uint8,
    )
    bsz = len(msg_hashes)
    za_in = np.concatenate(
        [np.broadcast_to(prefix, (bsz, len(prefix))), pubkeys], axis=1
    )
    za = sm3_batch([bytes(row) for row in za_in])
    e_in = np.concatenate([za, msg_hashes], axis=1)
    return sm3_batch([bytes(row) for row in e_in])


def verify_batch(
    msg_hashes: np.ndarray,
    rs: np.ndarray,
    ss: np.ndarray,
    pubkeys: np.ndarray,
    user_id: bytes = SM2_DEFAULT_ID,
) -> np.ndarray:
    """Host API: [B,32] tx hash, [B,32] r, [B,32] s, [B,64] pubkey -> bool[B]."""
    bsz = len(msg_hashes)
    bb = _bucket(bsz)
    e = _pad_rows(bytes_be_to_limbs(sm2_e_batch(msg_hashes, pubkeys, user_id)), bb)
    r = _pad_rows(bytes_be_to_limbs(rs), bb)
    s = _pad_rows(bytes_be_to_limbs(ss), bb)
    pubkeys = np.asarray(pubkeys, dtype=np.uint8)
    qx = _pad_rows(bytes_be_to_limbs(pubkeys[:, :32]), bb)
    qy = _pad_rows(bytes_be_to_limbs(pubkeys[:, 32:]), bb)
    out = verify_device(
        jnp.asarray(e), jnp.asarray(r), jnp.asarray(s), jnp.asarray(qx), jnp.asarray(qy)
    )
    return np.asarray(out)[:bsz]


def recover_batch(
    msg_hashes: np.ndarray, sigs_with_pub: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Reference-style SM2 "recover": signature is r‖s‖pubkey (128 bytes);
    parse the pubkey and verify (SM2Crypto.cpp:81-91).

    Returns (pubkeys [B,64], ok bool[B])."""
    sigs_with_pub = np.asarray(sigs_with_pub, dtype=np.uint8)
    pubs = sigs_with_pub[:, 64:128]
    ok = verify_batch(
        msg_hashes, sigs_with_pub[:, :32], sigs_with_pub[:, 32:64], pubs
    )
    out = np.where(ok[:, None], pubs, np.zeros_like(pubs))
    return out, ok
