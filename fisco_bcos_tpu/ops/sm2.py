"""Batch SM2 (GB/T 32918.2) signature verification on TPU — 国密 suite.

Reference counterpart: bcos-crypto signature/sm2/SM2Crypto.cpp:29-91 (wedpr
FFI) and the OpenSSL-tassl FastSM2 path (signature/fastsm2/fast_sm2.cpp).
Signature format follows the reference: 64-byte r‖s with the 64-byte
uncompressed public key appended, and "recover" = parse-pubkey-then-verify
(SM2Crypto.cpp:81-91) — SM2 has no algebraic pubkey recovery in this scheme.

The digest is e = SM3(ZA ‖ M) with ZA = SM3(ENTL ‖ ID ‖ a ‖ b ‖ Gx ‖ Gy ‖
Px ‖ Py) and the default user id "1234567812345678"; both are fixed-length
messages, so e-derivation itself runs on the batch SM3 kernel.

Verification: t = (r + s) mod n (t ≠ 0); (x1, y1) = s*G + t*Q;
valid iff (e + x1) mod n == r.

The EC plane is the limb-major windowed ladder shared with secp256k1
(:mod:`fisco_bcos_tpu.ops.ec`); SM2's prime has a 225-bit complement, so
the field is the generic Montgomery path (``limb.MontField``) by default.
The prime is also a Solinas prime (2^256 − p = 2^224 + 2^96 − 2^64 + 1),
and ``limb.SparseFoldField`` implements the shift-add fold bit-exactly —
opt in with FISCO_SM2_SPARSE=1 (kept off pending a measured win over
REDC; see the note in :func:`fisco_bcos_tpu.ops.ec._make_curve_ops`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.ref.ecdsa import SM2_DEFAULT_ID
from .bigint import bytes_be_to_limbs
from .ec import (
    SM2_OPS,
    add_mod_n,
    dual_mul_windowed,
    g_comb_table,
    lane_inv,
    on_curve,
    reduce_mod_n,
    valid_scalar,
)
from .hash_common import bucket_batch as _bucket
from .hash_common import pad_rows as _pad_rows
from .limb import const_rows, eq, is_zero, lt
from .sm3 import sm3_batch_async

_C = SM2_OPS


def verify_project_core(e, r, s, qx, qy, g_table):
    """Batch SM2 verify, projective part — Mosaic-compatible (runs inside
    the Pallas kernel on TPU, or plain XLA on CPU).

    Limb-major [16, T] plain-domain inputs: e = SM3(ZA ‖ M) digest as an
    integer; (r, s): signature; (qx, qy): affine public key.
    Returns (X, Z [16, T] Montgomery-domain projective coords of
    s*G + t*Q, valid bool[T]) — the final comparison needs the affine x1
    value, so the lane-batched Z inversion happens outside in
    :func:`verify_finish`."""
    C = _C
    F = C.F
    p_rows = const_rows(C.p_limbs, e)
    valid = valid_scalar(r, C) & valid_scalar(s, C)
    valid &= lt(qx, p_rows) & lt(qy, p_rows)
    qx_e = F.from_plain(qx)
    qy_e = F.from_plain(qy)
    valid &= on_curve(qx_e, qy_e, C)
    t = add_mod_n(reduce_mod_n(r, C), s, C)
    valid &= ~is_zero(t)
    X, _Y, Z = dual_mul_windowed(s, t, (qx_e, qy_e), C, g_table)
    return X, Z, valid


def verify_finish(e, r, X, Z, valid):
    """(e + x1) mod n == r with the Z inversion batched across lanes
    (plain XLA; one Fermat chain per batch)."""
    C = _C
    F = C.F
    zinv = lane_inv(F, Z)
    x1_e = F.mul(X, zinv)
    x1 = reduce_mod_n(F.to_plain(x1_e), C)
    e_n = reduce_mod_n(e, C)
    R = add_mod_n(e_n, x1, C)
    return valid & ~is_zero(Z) & eq(R, r)


def verify_core(e, r, s, qx, qy, g_table):
    """Whole-program SM2 verify (plain-XLA path)."""
    X, Z, valid = verify_project_core(e, r, s, qx, qy, g_table)
    return verify_finish(e, r, X, Z, valid)


@jax.jit
def _verify_xla(e, r, s, qx, qy):
    gt = jnp.asarray(g_comb_table(_C.name))
    return verify_core(e.T, r.T, s.T, qx.T, qy.T, gt)


def verify_device(e, r, s, qx, qy):
    """Batch SM2 verify. All inputs [B, 16] plain-domain batch-major limbs."""
    from .secp256k1 import _use_pallas, pallas_or_xla

    if _use_pallas():
        from .pallas_ec import sm2_verify_pallas

        return pallas_or_xla(sm2_verify_pallas, _verify_xla, e, r, s, qx, qy)
    return _verify_xla(e, r, s, qx, qy)


# ---------------------------------------------------------------------------
# Host wrappers
# ---------------------------------------------------------------------------


def sm2_e_batch(
    msg_hashes: np.ndarray, pubkeys: np.ndarray, user_id: bytes = SM2_DEFAULT_ID
) -> np.ndarray:
    """e = SM3(ZA ‖ M) for a batch: [B,32] hashes + [B,64] pubkeys -> [B,32].

    ZA inputs are fixed-length, so both SM3 passes run on the device kernel."""
    msg_hashes = np.asarray(msg_hashes, dtype=np.uint8)
    pubkeys = np.asarray(pubkeys, dtype=np.uint8)
    c = _C.curve
    entl = (len(user_id) * 8).to_bytes(2, "big")
    prefix = np.frombuffer(
        entl
        + user_id
        + c.a.to_bytes(32, "big")
        + c.b.to_bytes(32, "big")
        + c.gx.to_bytes(32, "big")
        + c.gy.to_bytes(32, "big"),
        dtype=np.uint8,
    )
    bsz = len(msg_hashes)
    za_in = np.concatenate(
        [np.broadcast_to(prefix, (bsz, len(prefix))), pubkeys], axis=1
    )
    # the span-less async entry: sm2_e_batch runs INSIDE the caller's
    # sm2_verify device_span — a nested sm3 span would double-count the
    # SM3 wall (and misfile its compiles as sm2 execute remainder); the
    # e-derivation is part of sm2's own phase decomposition
    za = sm3_batch_async([bytes(row) for row in za_in])()
    e_in = np.concatenate([za, msg_hashes], axis=1)
    return sm3_batch_async([bytes(row) for row in e_in])()


def verify_batch(
    msg_hashes: np.ndarray,
    rs: np.ndarray,
    ss: np.ndarray,
    pubkeys: np.ndarray,
    user_id: bytes = SM2_DEFAULT_ID,
) -> np.ndarray:
    """Host API: [B,32] tx hash, [B,32] r, [B,32] s, [B,64] pubkey -> bool[B]."""
    from ..observability.device import device_span

    bsz = len(msg_hashes)
    bb = _bucket(bsz)
    with device_span("sm2_verify", bsz, shape_key=bb) as sp:
        e = _pad_rows(
            bytes_be_to_limbs(sm2_e_batch(msg_hashes, pubkeys, user_id)), bb
        )
        r = _pad_rows(bytes_be_to_limbs(rs), bb)
        s = _pad_rows(bytes_be_to_limbs(ss), bb)
        pubkeys = np.asarray(pubkeys, dtype=np.uint8)
        qx = _pad_rows(bytes_be_to_limbs(pubkeys[:, :32]), bb)
        qy = _pad_rows(bytes_be_to_limbs(pubkeys[:, 32:]), bb)
        with sp.phase("transfer"):  # host->device staging of the operands
            ea, ra, sa = jnp.asarray(e), jnp.asarray(r), jnp.asarray(s)
            qxa, qya = jnp.asarray(qx), jnp.asarray(qy)
        out = verify_device(ea, ra, sa, qxa, qya)
        # analysis: allow(host-sync, wrapper-boundary materialization —
        # callers receive host bools; the plane overlaps batches, not lanes)
        return np.asarray(out)[:bsz]


def recover_batch(
    msg_hashes: np.ndarray, sigs_with_pub: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Reference-style SM2 "recover": signature is r‖s‖pubkey (128 bytes);
    parse the pubkey and verify (SM2Crypto.cpp:81-91).

    Returns (pubkeys [B,64], ok bool[B])."""
    sigs_with_pub = np.asarray(sigs_with_pub, dtype=np.uint8)
    pubs = sigs_with_pub[:, 64:128]
    ok = verify_batch(
        msg_hashes, sigs_with_pub[:, :32], sigs_with_pub[:, 32:64], pubs
    )
    out = np.where(ok[:, None], pubs, np.zeros_like(pubs))
    return out, ok


# -- progaudit shape spec (analysis/progaudit: canonical audited bucket) -----
PROGSPEC = {
    "_verify_xla": {
        "bucket": 256,
        "inputs": lambda b: [((b, 16), "uint32")] * 5,
    },
}
