"""Pallas TPU kernels for the batch EC signature programs.

The ``*_core`` bodies in :mod:`fisco_bcos_tpu.ops.secp256k1` are plain jnp
over limb-major ``[16, T]`` tiles, so the whole program — pseudo-Mersenne
field folds, Fermat inversions, the windowed ladder with its comb table —
runs inside one ``pallas_call`` with every intermediate VMEM-resident. Under
plain XLA the same chain of ~5k elementwise ops round-trips each [16, B]
intermediate through HBM; keeping it on-chip is worth an order of magnitude
(this was the main lever for the round-2 north-star target).

Grid: 1-D over batch tiles of ``TILE`` lanes; each program owns [16, TILE]
blocks of every operand. The affine GLV comb table for G and 2^128·G
([60, 16] uint32, :func:`fisco_bcos_tpu.ops.ec.g_comb_table_glv`) is
replicated into VMEM for every program. The batched scalar inversions
(r/s mod n, final Z mod p) run OUTSIDE the kernel as plain XLA
(:func:`fisco_bcos_tpu.ops.ec.lane_inv`) — Montgomery's trick needs
sub-vreg lane slicing Mosaic lacks, and the HBM round-trip of a few
[16, B] arrays is negligible next to the ~320-op per-lane Fermat chains
it deletes.

CPU/virtual-mesh execution never routes here (see ``_use_pallas``) — the XLA
path produces bit-identical results by integer semantics.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 256 lanes/program: at 512 the ladder's live set overflows the 16 MiB
# scoped-VMEM stack limit by ~4% (measured on v5e); 256 leaves headroom
MAX_TILE = 256
MIN_TILE = 128

# Test hook: run the kernels through the Pallas interpreter (CPU) so kernel
# semantics and the no-captured-constants restriction are exercised without
# TPU hardware. Toggled by tests; never set on the hot path.
INTERPRET = False


def _tile(b: int) -> int:
    for t in (MAX_TILE, MIN_TILE):
        if b % t == 0:
            return t
    raise ValueError(f"pallas EC batch must be a multiple of {MIN_TILE}, got {b}")


def _pad_lanes(x: jnp.ndarray, b_pad: int) -> jnp.ndarray:
    """Zero-pad the lane (minor) axis of [rows, B] to b_pad."""
    if x.shape[-1] == b_pad:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, b_pad - x.shape[-1])])


from .limb import mosaic_trace as _mosaic_trace


def _recover_kernel(
    z_ref, r_ref, s_ref, v_ref, rinv_ref, gt_ref, x_ref, y_ref, zz_ref, ok_ref
):
    from .secp256k1 import recover_project_core

    with _mosaic_trace():
        X, Y, Z, ok = recover_project_core(
            z_ref[:], r_ref[:], s_ref[:], v_ref[0], rinv_ref[:], gt_ref[:]
        )
    x_ref[:] = X
    y_ref[:] = Y
    zz_ref[:] = Z
    ok_ref[0] = ok.astype(jnp.int32)


def _verify_kernel(z_ref, r_ref, s_ref, qx_ref, qy_ref, sinv_ref, gt_ref, ok_ref):
    from .secp256k1 import verify_core

    with _mosaic_trace():
        ok = verify_core(
            z_ref[:], r_ref[:], s_ref[:], qx_ref[:], qy_ref[:],
            sinv_ref[:], gt_ref[:],
        )
    ok_ref[0] = ok.astype(jnp.int32)


def _limb_spec(tile: int):
    return pl.BlockSpec((16, tile), lambda i: (0, i), memory_space=pltpu.VMEM)


def _row_spec(tile: int):
    return pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM)


def _gt_spec():
    return pl.BlockSpec((60, 16), lambda i: (0, 0), memory_space=pltpu.VMEM)


@lru_cache(maxsize=None)
def _recover_call(b: int, interpret: bool = False):
    tile = _tile(b)

    @jax.jit
    def run(z, r, s, v, gt):
        from .secp256k1 import inv_mod_n, recover_finish

        rinv = inv_mod_n(r)  # batched Fermat, outside the kernel
        X, Y, Z, ok = pl.pallas_call(
            _recover_kernel,
            interpret=interpret,
            grid=(b // tile,),
            in_specs=[
                _limb_spec(tile),
                _limb_spec(tile),
                _limb_spec(tile),
                _row_spec(tile),
                _limb_spec(tile),
                _gt_spec(),
            ],
            out_specs=(
                _limb_spec(tile),
                _limb_spec(tile),
                _limb_spec(tile),
                _row_spec(tile),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((16, b), jnp.uint32),
                jax.ShapeDtypeStruct((16, b), jnp.uint32),
                jax.ShapeDtypeStruct((16, b), jnp.uint32),
                jax.ShapeDtypeStruct((1, b), jnp.int32),
            ),
        )(z, r, s, v, rinv, gt)
        qx, qy, okf = recover_finish(X, Y, Z, ok[0] != 0)
        return qx.T, qy.T, okf

    return run


@lru_cache(maxsize=None)
def _verify_call(b: int, interpret: bool = False):
    tile = _tile(b)

    @jax.jit
    def run(z, r, s, qx, qy, gt):
        from .secp256k1 import inv_mod_n

        sinv = inv_mod_n(s)
        ok = pl.pallas_call(
            _verify_kernel,
            interpret=interpret,
            grid=(b // tile,),
            in_specs=[_limb_spec(tile)] * 5 + [_limb_spec(tile), _gt_spec()],
            out_specs=_row_spec(tile),
            out_shape=jax.ShapeDtypeStruct((1, b), jnp.int32),
        )(z, r, s, qx, qy, sinv, gt)
        return ok[0] != 0

    return run


def _sm2_verify_kernel(e_ref, r_ref, s_ref, qx_ref, qy_ref, gt_ref, x_ref, z_ref, ok_ref):
    from .sm2 import verify_project_core

    with _mosaic_trace():
        X, Z, ok = verify_project_core(
            e_ref[:], r_ref[:], s_ref[:], qx_ref[:], qy_ref[:], gt_ref[:]
        )
    x_ref[:] = X
    z_ref[:] = Z
    ok_ref[0] = ok.astype(jnp.int32)


def _sm2_gt_spec():
    return pl.BlockSpec((30, 16), lambda i: (0, 0), memory_space=pltpu.VMEM)


# SM2's Montgomery field triples the per-mul intermediates of the secp
# pseudo-Mersenne fold; half the lane tile keeps the ladder's live set
# inside the scoped-VMEM budget
SM2_TILE = 128


@lru_cache(maxsize=None)
def _sm2_verify_call(b: int, interpret: bool = False):
    if b % SM2_TILE:
        raise ValueError(f"SM2 pallas batch must be a multiple of {SM2_TILE}, got {b}")
    tile = SM2_TILE

    @jax.jit
    def run(e, r, s, qx, qy, gt):
        from .sm2 import verify_finish

        X, Z, ok = pl.pallas_call(
            _sm2_verify_kernel,
            interpret=interpret,
            grid=(b // tile,),
            in_specs=[_limb_spec(tile)] * 5 + [_sm2_gt_spec()],
            out_specs=(
                _limb_spec(tile),
                _limb_spec(tile),
                _row_spec(tile),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((16, b), jnp.uint32),
                jax.ShapeDtypeStruct((16, b), jnp.uint32),
                jax.ShapeDtypeStruct((1, b), jnp.int32),
            ),
        )(e, r, s, qx, qy, gt)
        return verify_finish(e, r, X, Z, ok[0] != 0)

    return run


def sm2_verify_pallas(e, r, s, qx, qy):
    """[B, 16] batch-major limb inputs -> ok bool[B] (SM2)."""
    from .ec import g_comb_table
    from .sm2 import SM2_OPS

    b = e.shape[0]
    b_pad = max(MIN_TILE, -(-b // MIN_TILE) * MIN_TILE)
    gt = jnp.asarray(g_comb_table(SM2_OPS.name))
    ok = _sm2_verify_call(b_pad, INTERPRET)(
        _pad_lanes(jnp.asarray(e).T, b_pad),
        _pad_lanes(jnp.asarray(r).T, b_pad),
        _pad_lanes(jnp.asarray(s).T, b_pad),
        _pad_lanes(jnp.asarray(qx).T, b_pad),
        _pad_lanes(jnp.asarray(qy).T, b_pad),
        gt,
    )
    return ok[:b]


def recover_pallas(z, r, s, v):
    """[B, 16] batch-major limbs + [B] v -> (qx, qy [B, 16], ok bool[B])."""
    from .ec import g_comb_table_glv
    from .secp256k1 import SECP256K1_OPS

    b = z.shape[0]
    b_pad = max(MIN_TILE, -(-b // MIN_TILE) * MIN_TILE)
    gt = jnp.asarray(g_comb_table_glv(SECP256K1_OPS.name))
    qx, qy, ok = _recover_call(b_pad, INTERPRET)(
        _pad_lanes(jnp.asarray(z).T, b_pad),
        _pad_lanes(jnp.asarray(r).T, b_pad),
        _pad_lanes(jnp.asarray(s).T, b_pad),
        _pad_lanes(jnp.asarray(v).reshape(1, b).astype(jnp.int32), b_pad),
        gt,
    )
    return qx[:b], qy[:b], ok[:b]


def verify_pallas(z, r, s, qx, qy):
    """[B, 16] batch-major limb inputs -> ok bool[B]."""
    from .ec import g_comb_table_glv
    from .secp256k1 import SECP256K1_OPS

    b = z.shape[0]
    b_pad = max(MIN_TILE, -(-b // MIN_TILE) * MIN_TILE)
    gt = jnp.asarray(g_comb_table_glv(SECP256K1_OPS.name))
    ok = _verify_call(b_pad, INTERPRET)(
        _pad_lanes(jnp.asarray(z).T, b_pad),
        _pad_lanes(jnp.asarray(r).T, b_pad),
        _pad_lanes(jnp.asarray(s).T, b_pad),
        _pad_lanes(jnp.asarray(qx).T, b_pad),
        _pad_lanes(jnp.asarray(qy).T, b_pad),
        gt,
    )
    return ok[:b]


# -- progaudit shape spec: pallas kernels never trace off-TPU --------------
PROGSPEC = {
    "_recover_call.run": {"skip": "pallas kernels are TPU-only"},
    "_verify_call.run": {"skip": "pallas kernels are TPU-only"},
    "_sm2_verify_call.run": {"skip": "pallas kernels are TPU-only"},
}
