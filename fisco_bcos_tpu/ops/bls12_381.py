"""BLS12-381 pairing kernels — aggregate-QC verification on device.

The fourth signature plane (after secp256k1/SM2/Ed25519): one jitted
program runs the whole quorum-certificate pairing check
``e(-g1, agg_sig) * e(agg_pk, H(m)) == 1`` for a batch of certificates —
the constant-size QC admission that makes committee size a free variable
(ROADMAP aggregate-signature item; EdDSA-vs-BLS committee study
arXiv:2302.00418, ByzCoin collective signing 1602.06997).

Split of labor (the ed25519.py precedent):
- **Host**: hash-to-G2 (SHA-256 try-and-increment + cofactor clearing —
  per quorum MESSAGE, one per header, cached in the reference), point
  decompression/subgroup checks (per committee member, cached by the
  crypto seam), byte→limb packing.
- **Device**: the pairing itself — shared-squaring double Miller loop
  with denominator-eliminated line evaluation, and the full final
  exponentiation (easy part with a tower inversion, hard part as a
  square-and-multiply scan over the static 3(p^4-p^2+1)/r bits).

TPU-first formulation, one deliberate divergence from the 256-bit
kernels: Fp is 381 bits, so elements are **24 little-endian 16-bit limbs
in [24, T] limb-major arrays** with word-Montgomery reduction (R = 2^384)
— the pseudo-Mersenne folding of :mod:`.limb` does not apply to this
prime. The generic carry/compare machinery of :mod:`.limb` is width-
agnostic and reused as-is; only the multiply/reduce pair is local.

Tower: Fp2 = Fp[u]/(u²+1), Fp6 = Fp2[v]/(v³-ξ), Fp12 = Fp6[w]/(w²-v),
ξ = 1+u. Frobenius rides host-precomputed γ constants COMPUTED (not
transcribed) from the pure-Python reference; every tower identity the
kernel relies on is cross-checked against the reference's independent
polynomial-basis Fp12 in tests, through the trivial change of basis.

G2 accumulators stay in Jacobian coordinates on the twist (the same
dbl-2009-l / madd-2007-bl formulas the reference's fast path uses);
line normalization factors live in final-exponentiation-killed subfields,
so no inversion appears anywhere in the Miller loop. The one inversion
in the easy part uses the standard tower-norm descent.

Compile cost is real (~an ed25519-sized scan body plus the final-exp
scans) and paid once per shape bucket into the persistent jit cache;
CPU backends never compile it — the crypto seam routes them to the
bit-identical host reference (use_native_batch), exactly like the other
curves.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.ref import bls12_381 as ref
from . import limb
from .hash_common import bucket_batch as _bucket
from .hash_common import pad_rows as _pad_rows
from .limb import _placed, add_widen, carry_norm, eq, select, sub_borrow

P = ref.P
NL = 24  # 381-bit field -> 24 x 16-bit limbs
R384 = 1 << 384

_P_LIMBS = limb.int_to_rows(P, NL)
_MPRIME_LIMBS = limb.int_to_rows((-pow(P, -1, R384)) % R384, NL)
_MASK = np.uint32(0xFFFF)

# Miller loop bits: |x|, MSB first, leading bit consumed by initialization
_X_ABS_BITS = np.array(
    [int(b) for b in bin(-ref.X_PARAM)[2:]][1:], dtype=np.int32
)
# hard-part exponent 3(p^4-p^2+1)/r, MSB first (identity asserted in ref)
_H3 = 3 * ((P**4 - P**2 + 1) // ref.R_ORDER)
_H3_BITS = np.array([int(b) for b in bin(_H3)[2:]][1:], dtype=np.int32)


def _mont(x: int) -> np.ndarray:
    """int -> Montgomery-domain limb row [24]."""
    return limb.int_to_rows(x * R384 % P, NL)


def _crows(limbs_np: np.ndarray, like: jax.Array) -> jax.Array:
    return limb.const_rows(limbs_np, like)


def _cond_sub24(x: jax.Array) -> jax.Array:
    """x < 2p (any width >= 24) -> x mod p as 24 limbs."""
    w = x.shape[0]
    m_pad = np.zeros(w, dtype=np.uint32)
    m_pad[:NL] = _P_LIMBS
    diff, borrow = sub_borrow(x, _crows(m_pad, x))
    return select(~borrow, diff, x)[:NL]


def _mul_cols24(a: jax.Array, b: jax.Array, out: int) -> jax.Array:
    """Column sums of a*b for 24-limb rows ([24, T] x [24, T] -> [out, T]).
    48 sub-2^16 terms per column keeps sums inside carry_norm's 2^22
    two-pass budget."""
    terms = []
    for i in range(NL):
        prod = lax.slice_in_dim(a, i, i + 1, axis=0) * b  # [24, T] < 2^32
        terms.append(_placed(prod & _MASK, i, out))
        terms.append(_placed(prod >> 16, i + 1, out))
    return limb._sum_terms(terms)


class Fp:
    """GF(p) for the 381-bit prime, Montgomery domain, 24-limb rows.
    Presents the same ops protocol as limb.MontField so pow_static-style
    generic code composes."""

    @staticmethod
    def redc(t: jax.Array) -> jax.Array:
        """t [48, T] (t < p*R) -> t/R mod p [24, T] (word Montgomery)."""
        m_val = carry_norm(
            _mul_cols24(t[:NL], _crows(_MPRIME_LIMBS, t), out=NL)
        )[:NL]
        mm = carry_norm(_mul_cols24(m_val, _crows(_P_LIMBS, t), out=2 * NL))[
            : 2 * NL
        ]
        s = add_widen(t, mm)  # [49, T]; low 24 limbs are zero
        return _cond_sub24(s[NL:])

    @staticmethod
    def mul(a: jax.Array, b: jax.Array) -> jax.Array:
        return Fp.redc(carry_norm(_mul_cols24(a, b, out=2 * NL))[: 2 * NL])

    @staticmethod
    def sqr(a: jax.Array) -> jax.Array:
        return Fp.mul(a, a)

    @staticmethod
    def add(a: jax.Array, b: jax.Array) -> jax.Array:
        return _cond_sub24(add_widen(a, b))

    @staticmethod
    def sub(a: jax.Array, b: jax.Array) -> jax.Array:
        diff, borrow = sub_borrow(a, b)
        plus = add_widen(diff, _crows(_P_LIMBS, a))[:NL]
        return select(borrow, plus, diff)

    @staticmethod
    def neg(a: jax.Array) -> jax.Array:
        return Fp.sub(jnp.zeros_like(a), a)

    @staticmethod
    def muli(a: jax.Array, k: int) -> jax.Array:
        """a * k for tiny k via an addition chain (Montgomery-compatible)."""
        assert 0 < k < 32
        acc = None
        for bit in bin(k)[2:]:
            if acc is not None:
                acc = Fp.add(acc, acc)
            if bit == "1":
                acc = a if acc is None else Fp.add(acc, a)
        return acc

    @staticmethod
    def one(like: jax.Array) -> jax.Array:
        return _crows(_mont(1), like)

    @staticmethod
    def zero(like: jax.Array) -> jax.Array:
        return jnp.zeros((NL, like.shape[-1]), jnp.uint32)


def fp_inv(a: jax.Array) -> jax.Array:
    """a^-1 via Fermat (static 381-bit exponent, scan-shaped windows)."""
    return limb.pow_static(Fp, a, P - 2)


# ---------------------------------------------------------------------------
# Fp2 (pairs), Fp6 (triples of pairs), Fp12 (pairs of triples of pairs)
# ---------------------------------------------------------------------------


def f2_add(a, b):
    return (Fp.add(a[0], b[0]), Fp.add(a[1], b[1]))


def f2_sub(a, b):
    return (Fp.sub(a[0], b[0]), Fp.sub(a[1], b[1]))


def f2_neg(a):
    return (Fp.neg(a[0]), Fp.neg(a[1]))


def f2_conj(a):
    return (a[0], Fp.neg(a[1]))


def f2_mul(a, b):
    v0 = Fp.mul(a[0], b[0])
    v1 = Fp.mul(a[1], b[1])
    c1 = Fp.sub(
        Fp.mul(Fp.add(a[0], a[1]), Fp.add(b[0], b[1])), Fp.add(v0, v1)
    )
    return (Fp.sub(v0, v1), c1)


def f2_sqr(a):
    c0 = Fp.mul(Fp.add(a[0], a[1]), Fp.sub(a[0], a[1]))
    c1 = Fp.muli(Fp.mul(a[0], a[1]), 2)
    return (c0, c1)


def f2_muli(a, k: int):
    return (Fp.muli(a[0], k), Fp.muli(a[1], k))


def f2_mul_xi(a):
    """a * (1 + u): ((c0 - c1), (c0 + c1))."""
    return (Fp.sub(a[0], a[1]), Fp.add(a[0], a[1]))


def f2_inv(a):
    n = Fp.add(Fp.sqr(a[0]), Fp.sqr(a[1]))
    ni = fp_inv(n)
    return (Fp.mul(a[0], ni), Fp.neg(Fp.mul(a[1], ni)))


def f2_zero(like):
    return (Fp.zero(like), Fp.zero(like))


def f2_one(like):
    return (Fp.one(like), Fp.zero(like))


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def f6_mul(a, b):
    v0 = f2_mul(a[0], b[0])
    v1 = f2_mul(a[1], b[1])
    v2 = f2_mul(a[2], b[2])
    t0 = f2_mul(f2_add(a[1], a[2]), f2_add(b[1], b[2]))
    c0 = f2_add(v0, f2_mul_xi(f2_sub(t0, f2_add(v1, v2))))
    t1 = f2_mul(f2_add(a[0], a[1]), f2_add(b[0], b[1]))
    c1 = f2_add(f2_sub(t1, f2_add(v0, v1)), f2_mul_xi(v2))
    t2 = f2_mul(f2_add(a[0], a[2]), f2_add(b[0], b[2]))
    c2 = f2_add(f2_sub(t2, f2_add(v0, v2)), v1)
    return (c0, c1, c2)


def f6_mul_by_01(a, b0, b1):
    """a * (b0 + b1 v) sparse (line's Fp6 half)."""
    v0 = f2_mul(a[0], b0)
    v1 = f2_mul(a[1], b1)
    c0 = f2_add(v0, f2_mul_xi(f2_mul(a[2], b1)))
    c1 = f2_add(f2_mul(a[1], b0), f2_mul(a[0], b1))
    c2 = f2_add(f2_mul(a[2], b0), v1)
    return (c0, c1, c2)


def f6_mul_by_1(a, b1):
    """a * (b1 v)."""
    return (
        f2_mul_xi(f2_mul(a[2], b1)),
        f2_mul(a[0], b1),
        f2_mul(a[1], b1),
    )


def f6_mul_v(a):
    """a * v (rotate with xi)."""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_zero(like):
    z = f2_zero(like)
    return (z, z, z)


def f6_one(like):
    return (f2_one(like), f2_zero(like), f2_zero(like))


def f6_inv(a):
    """Standard v³=ξ tower inversion (cross-checked against the reference's
    polynomial-basis Euclid in tests)."""
    c0 = f2_sub(f2_sqr(a[0]), f2_mul_xi(f2_mul(a[1], a[2])))
    c1 = f2_sub(f2_mul_xi(f2_sqr(a[2])), f2_mul(a[0], a[1]))
    c2 = f2_sub(f2_sqr(a[1]), f2_mul(a[0], a[2]))
    t = f2_add(
        f2_mul(a[0], c0),
        f2_mul_xi(f2_add(f2_mul(a[1], c2), f2_mul(a[2], c1))),
    )
    ti = f2_inv(t)
    return (f2_mul(c0, ti), f2_mul(c1, ti), f2_mul(c2, ti))


def f12_mul(a, b):
    g1, h1 = a
    g2, h2 = b
    vg = f6_mul(g1, g2)
    vh = f6_mul(h1, h2)
    w_part = f6_sub(f6_sub(f6_mul(f6_add(g1, h1), f6_add(g2, h2)), vg), vh)
    return (f6_add(vg, f6_mul_v(vh)), w_part)


def f12_sqr(a):
    g, h = a
    v0 = f6_mul(g, h)
    t = f6_mul(f6_add(g, h), f6_add(g, f6_mul_v(h)))
    c0 = f6_sub(f6_sub(t, v0), f6_mul_v(v0))
    return (c0, f6_add(v0, v0))


def f12_inv(a):
    g, h = a
    t = f6_inv(f6_sub(f6_mul(g, g), f6_mul_v(f6_mul(h, h))))
    return (f6_mul(g, t), f6_neg(f6_mul(h, t)))


def f12_one(like):
    return (f6_one(like), f6_zero(like))


def f12_mul_line(f, c0, c2, c3):
    """f * ((c0 + c2 v) + (c3 v) w) — the sparse line element (Fp2 coeffs
    at w^0, w^2, w^3 in flat-basis terms), Karatsuba over the w split."""
    g, h = f
    lg0, lg1 = c0, c2
    a = f6_mul_by_01(g, lg0, lg1)
    b = f6_mul_by_1(h, c3)
    sum_l1 = f2_add(lg1, c3)
    c = f6_mul_by_01(f6_add(g, h), lg0, sum_l1)
    w_part = f6_sub(f6_sub(c, a), b)
    return (f6_add(a, f6_mul_v(b)), w_part)


def f12_eq_one(a) -> jax.Array:
    """[T] bool: a == 1 (coefficient-wise against Montgomery 1/0)."""
    g, h = a
    like = g[0][0]
    ok = eq(g[0][0], _crows(_mont(1), like))
    ok &= limb.is_zero(g[0][1])
    for c in (g[1], g[2], h[0], h[1], h[2]):
        ok &= limb.is_zero(c[0]) & limb.is_zero(c[1])
    return ok


# ---------------------------------------------------------------------------
# Frobenius (host-computed gamma constants, applied as Fp2 constant muls)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _frob_consts(k: int):
    """gamma[k][(a, b)] = xi^(a (p^k - 1)/3 + b (p^k - 1)/6) in Fp2 for the
    six tower monomials v^a w^b — computed with the reference's exact
    integer arithmetic, converted to Montgomery rows."""
    out = {}
    for a_pow in range(3):
        for b_pow in range(2):
            e = a_pow * (P**k - 1) // 3 + b_pow * (P**k - 1) // 6
            g = _f2_pow_ref(ref.XI, e)
            out[(a_pow, b_pow)] = (_mont(g[0]), _mont(g[1]))
    return out


def _f2_pow_ref(a, e: int):
    out = ref.F2_ONE
    while e:
        if e & 1:
            out = ref.f2_mul(out, a)
        a = ref.f2_sqr(a)
        e >>= 1
    return out


def f12_frob(f, k: int):
    """f^(p^k) in the tower: conjugate Fp2 coefficients (k odd) then scale
    each monomial by its gamma constant."""
    consts = _frob_consts(k)
    g, h = f
    like = g[0][0]
    out_g, out_h = [], []
    for a_pow in range(3):
        for b_pow, (src, dst) in ((0, (g, out_g)), (1, (h, out_h))):
            c = src[a_pow]
            if k % 2:
                c = f2_conj(c)
            gm = consts[(a_pow, b_pow)]
            gm_rows = (_crows(gm[0], like), _crows(gm[1], like))
            dst.append(f2_mul(c, gm_rows))
    return (tuple(out_g), tuple(out_h))


# ---------------------------------------------------------------------------
# Jacobian point ops (generic over the field: G1 on Fp, G2 on Fp2)
# ---------------------------------------------------------------------------


class _F2Ops:
    add = staticmethod(f2_add)
    sub = staticmethod(f2_sub)
    mul = staticmethod(f2_mul)
    sqr = staticmethod(f2_sqr)
    muli = staticmethod(f2_muli)


class _FpOps:
    add = staticmethod(Fp.add)
    sub = staticmethod(Fp.sub)
    mul = staticmethod(Fp.mul)
    sqr = staticmethod(Fp.sqr)
    muli = staticmethod(Fp.muli)


def jac_double(F, X, Y, Z):
    """dbl-2009-l (a = 0) — same formulas as the reference fast path."""
    A = F.sqr(X)
    B = F.sqr(Y)
    C = F.sqr(B)
    D = F.muli(F.sub(F.sub(F.sqr(F.add(X, B)), A), C), 2)
    E = F.muli(A, 3)
    X3 = F.sub(F.sqr(E), F.muli(D, 2))
    Y3 = F.sub(F.mul(E, F.sub(D, X3)), F.muli(C, 8))
    Z3 = F.muli(F.mul(Y, Z), 2)
    return X3, Y3, Z3


def jac_add_affine(F, X, Y, Z, x2, y2):
    """madd-2007-bl mixed addition (no exceptional-case handling: inside
    the ate loop T = kQ never meets ±Q for valid r-torsion inputs, and
    invalid inputs only need a deterministic wrong answer)."""
    Z1Z1 = F.sqr(Z)
    U2 = F.mul(x2, Z1Z1)
    S2 = F.mul(F.mul(y2, Z), Z1Z1)
    H = F.sub(U2, X)
    r = F.muli(F.sub(S2, Y), 2)
    HH = F.sqr(H)
    I = F.muli(HH, 4)
    J = F.mul(H, I)
    V = F.mul(X, I)
    X3 = F.sub(F.sub(F.sqr(r), J), F.muli(V, 2))
    Y3 = F.sub(F.mul(r, F.sub(V, X3)), F.muli(F.mul(Y, J), 2))
    Z3 = F.sub(F.sub(F.sqr(F.add(Z, H)), Z1Z1), HH)
    return X3, Y3, Z3


g1_double = lambda X, Y, Z: jac_double(_FpOps, X, Y, Z)  # noqa: E731
g1_add_affine = lambda X, Y, Z, x, y: jac_add_affine(_FpOps, X, Y, Z, x, y)  # noqa: E731
g2_double = lambda X, Y, Z: jac_double(_F2Ops, X, Y, Z)  # noqa: E731
g2_add_affine = lambda X, Y, Z, x, y: jac_add_affine(_F2Ops, X, Y, Z, x, y)  # noqa: E731


def _dbl_step(T, xp, yp):
    """One doubling step: new T and the (c0, c2, c3) line coefficients
    (denominator-eliminated tangent at T, evaluated at the G1 point):
    c0 = 3X³ - 2Y², c2 = -3X²Z² · xp, c3 = 2YZ³ · yp."""
    X, Y, Z = T
    X2 = f2_sqr(X)
    Z2 = f2_sqr(Z)
    c0 = f2_sub(f2_muli(f2_mul(X2, X), 3), f2_muli(f2_sqr(Y), 2))
    x2z2_3 = f2_muli(f2_mul(X2, Z2), 3)
    c2 = (Fp.neg(Fp.mul(x2z2_3[0], xp)), Fp.neg(Fp.mul(x2z2_3[1], xp)))
    yz3 = f2_muli(f2_mul(Y, f2_mul(Z, Z2)), 2)
    c3 = (Fp.mul(yz3[0], yp), Fp.mul(yz3[1], yp))
    return g2_double(X, Y, Z), (c0, c2, c3)


def _add_step(T, q, xp, yp):
    """One mixed-addition step: new T and the chord line through T and the
    affine Q: with N = Y - yq Z³, D = X - xq Z²:
    c0 = N xq - D Z yq, c2 = -N · xp, c3 = D Z · yp."""
    X, Y, Z = T
    xq, yq = q
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z, Z2)
    N = f2_sub(Y, f2_mul(yq, Z3))
    D = f2_sub(X, f2_mul(xq, Z2))
    DZ = f2_mul(D, Z)
    c0 = f2_sub(f2_mul(N, xq), f2_mul(DZ, yq))
    c2 = (Fp.neg(Fp.mul(N[0], xp)), Fp.neg(Fp.mul(N[1], xp)))
    c3 = (Fp.mul(DZ[0], yp), Fp.mul(DZ[1], yp))
    return g2_add_affine(X, Y, Z, xq, yq), (c0, c2, c3)


# ---------------------------------------------------------------------------
# Miller loop + final exponentiation
# ---------------------------------------------------------------------------


def _miller2(p1, q1, p2, q2):
    """f_{|x|}(P1, Q1) * f_{|x|}(P2, Q2) with shared squaring, conjugated
    for the negative parameter. p_i = (xp, yp) Fp rows; q_i = (x, y) Fp2
    affine on the twist."""
    like = p1[0]
    one = f12_one(like)

    def t_init(q):
        return (q[0], q[1], f2_one(like))

    def body(carry, bit):
        f, t1, t2 = carry
        f = f12_sqr(f)
        t1n, l1 = _dbl_step(t1, p1[0], p1[1])
        f = f12_mul_line(f, *l1)
        t2n, l2 = _dbl_step(t2, p2[0], p2[1])
        f = f12_mul_line(f, *l2)
        t1a, l1a = _add_step(t1n, q1, p1[0], p1[1])
        t2a, l2a = _add_step(t2n, q2, p2[0], p2[1])
        f_add = f12_mul_line(f12_mul_line(f, *l1a), *l2a)
        take = bit == 1
        f = select(take, f_add, f)
        t1 = select(take, t1a, t1n)
        t2 = select(take, t2a, t2n)
        return (f, t1, t2), None

    carry, _ = lax.scan(
        body, (one, t_init(q1), t_init(q2)), limb.dev_vec(_X_ABS_BITS)
    )
    return f12_frob(carry[0], 6)  # x < 0 -> conjugate


def _miller1(p, q):
    """f_{|x|}(P, Q), conjugated for the negative parameter — the single-
    pair Miller loop the multi-pairing product program is built from
    (same dbl/add steps as :func:`_miller2`, one accumulator per lane)."""
    like = p[0]
    one = f12_one(like)

    def body(carry, bit):
        f, t = carry
        f = f12_sqr(f)
        tn, l = _dbl_step(t, p[0], p[1])
        f = f12_mul_line(f, *l)
        ta, la = _add_step(tn, q, p[0], p[1])
        f_add = f12_mul_line(f, *la)
        take = bit == 1
        f = select(take, f_add, f)
        t = select(take, ta, tn)
        return (f, t), None

    carry, _ = lax.scan(
        body, (one, (q[0], q[1], f2_one(like))), limb.dev_vec(_X_ABS_BITS)
    )
    return f12_frob(carry[0], 6)  # x < 0 -> conjugate


def _final_exp(f):
    """Easy part (p^6-1)(p^2+1) then the hard part as square-and-multiply
    over the static bits of 3(p^4-p^2+1)/r — compile-lean (one small scan
    body) at ~1.9k Fp12 ops runtime; the batched lanes amortize it."""
    m = f12_mul(f12_frob(f, 6), f12_inv(f))
    m = f12_mul(f12_frob(m, 2), m)

    def body(acc, bit):
        acc = f12_sqr(acc)
        with_mul = f12_mul(acc, m)
        return select(bit == 1, with_mul, acc), None

    out, _ = lax.scan(body, m, limb.dev_vec(_H3_BITS))
    return out


def pairing_check_core(
    apk_x, apk_y, sx0, sx1, sy0, sy1, hx0, hx1, hy0, hy1
):
    """ok[T] for e(-g1, sig) * e(apk, Hm) == 1 over [24, T] Montgomery
    limb inputs (apk in Fp, sig/Hm in Fp2-pairs)."""
    like = apk_x
    neg_g1 = (
        _crows(_mont(ref.G1_X), like),
        _crows(_mont((-ref.G1_Y) % P), like),
    )
    f = _miller2(
        neg_g1,
        ((sx0, sx1), (sy0, sy1)),
        (apk_x, apk_y),
        ((hx0, hx1), (hy0, hy1)),
    )
    return f12_eq_one(_final_exp(f))


@jax.jit
def _pairing_check_xla(apk_x, apk_y, sx0, sx1, sy0, sy1, hx0, hx1, hy0, hy1):
    return pairing_check_core(
        apk_x.T, apk_y.T, sx0.T, sx1.T, sy0.T, sy1.T,
        hx0.T, hx1.T, hy0.T, hy1.T,
    )


@jax.jit
def _multi_pairing_xla(px, py, qx0, qx1, qy0, qy1, valid):
    """ok[1] for ∏_i e(P_i, Q_i) == 1 over [B, 24] Montgomery limb inputs
    (B a power of two; P_i in G1, Q_i affine Fp2 on the twist).

    B lane-parallel Miller loops, then a log₂-depth ``f12_mul`` halving
    tree over the lane axis, then ONE final exponentiation — K pairs cost
    K/lanes of a Miller loop plus a single hard part, which is where the
    constant-work header sync gets its per-device speedup.

    ``valid`` is a DEVICE argument, not host-side post-masking: an invalid
    or padding lane multiplies into the product, so it must become the
    Fp12 identity before the tree — a host mask after the fact could not
    undo its contribution."""
    f = _miller1((px.T, py.T), ((qx0.T, qx1.T), (qy0.T, qy1.T)))
    f = select(valid, f, f12_one(px.T))
    n = px.shape[0]
    while n > 1:
        half = n // 2
        lo = jax.tree_util.tree_map(lambda x: x[:, :half], f)
        hi = jax.tree_util.tree_map(lambda x: x[:, half:], f)
        f = f12_mul(lo, hi)
        n = half
    return f12_eq_one(_final_exp(f))


# ---------------------------------------------------------------------------
# Host wrapper
# ---------------------------------------------------------------------------

# masked-out lanes get well-formed but non-verifying substitutes (distinct
# multiples of the generators), so even a masking bug cannot turn an
# invalid lane into an accepting one
_SUB_APK = ref.G1
_SUB_SIG = ref.G2
_SUB_HM = ref.ec_mul(ref.G2, 2, ref.FP2_OPS)


def _mont_col(vals: list[int]) -> np.ndarray:
    """list of B ints -> [B, 24] Montgomery rows."""
    return np.stack([_mont(v) for v in vals]).astype(np.uint32)


def device_inputs(checks, pad_to: int | None = None):
    """checks: [(apk_pt | None, sig_pt | None, hm_pt)] affine reference
    points -> (10 x [B', 24] Montgomery arrays, valid [B'] bool), batch
    bucket-padded. None points invalidate their lane."""
    bsz = len(checks)
    bb = pad_to if pad_to is not None else _bucket(max(bsz, 1))
    cols = [[] for _ in range(10)]
    valid = np.zeros(bb, dtype=bool)
    for i in range(bb):
        if i < bsz and all(pt is not None for pt in checks[i]):
            apk, sig, hm = checks[i]
            valid[i] = True
        else:
            apk, sig, hm = _SUB_APK, _SUB_SIG, _SUB_HM
        vals = [
            apk[0], apk[1],
            sig[0][0], sig[0][1], sig[1][0], sig[1][1],
            hm[0][0], hm[0][1], hm[1][0], hm[1][1],
        ]
        for c, v in zip(cols, vals):
            c.append(v)
    arrays = [_mont_col(c) for c in cols]
    return arrays, valid


def pairing_check_batch(checks) -> np.ndarray:
    """Host API: list of (apk, sig, hm) affine point triples (reference
    representation: G1 int pairs, G2 Fp2-tuple pairs; None = invalid) ->
    bool[B]. One jitted device program for the whole batch."""
    bsz = len(checks)
    if bsz == 0:
        return np.zeros(0, dtype=bool)
    arrays, valid = device_inputs(checks)
    padded = [_pad_rows(a, valid.shape[0]) for a in arrays]
    # analysis: allow(host-sync, QC admission consumes the verdict bits
    # synchronously — this IS the pairing call's contract boundary)
    ok = np.asarray(_pairing_check_xla(*padded))
    return (ok & valid)[:bsz]


def host_pairing_check_batch(checks) -> np.ndarray:
    """Bit-identical host fallback (the reference pairing), same contract."""
    out = np.zeros(len(checks), dtype=bool)
    for i, (apk, sig, hm) in enumerate(checks):
        if apk is None or sig is None or hm is None:
            continue
        out[i] = ref.pairing_check(
            [(ref.ec_neg(ref.G1, ref.FP_OPS), sig), (apk, hm)]
        )
    return out


# non-verifying substitute pair for multi-pairing padding lanes: e(G1, G2)
# != 1, so even a masking bug cannot make a padding lane contribute the
# identity — it would flip the product to a REJECT, never an accept
_SUB_PAIR = (ref.G1, ref.G2)


def multi_pairing_pad(n: int) -> int:
    """Lane count the multi-pairing program pads an n-pair product to: the
    next power of two (the halving tree's shape), min 1 — the compiled-
    shape ladder is the log₂ sequence, not the batch bucket ladder."""
    b = 1
    while b < max(n, 1):
        b *= 2
    return b


def multi_pairing_check(pairs) -> bool:
    """True iff ∏ e(P_i, Q_i) == 1 for a list of (g1_pt, g2_pt) affine
    reference points. One jitted device program: lane-parallel Miller
    loops, an on-device product tree, ONE final exponentiation. ``None``
    members make their pair an identity contribution — the
    :func:`ref.pairing_check` convention."""
    if not pairs:
        return True
    bb = multi_pairing_pad(len(pairs))
    cols: list[list[int]] = [[] for _ in range(6)]
    valid = np.zeros(bb, dtype=bool)
    for i in range(bb):
        if (
            i < len(pairs)
            and pairs[i][0] is not None
            and pairs[i][1] is not None
        ):
            p, q = pairs[i]
            valid[i] = True
        else:
            p, q = _SUB_PAIR
        vals = [p[0], p[1], q[0][0], q[0][1], q[1][0], q[1][1]]
        for c, v in zip(cols, vals):
            c.append(v)
    arrays = [_mont_col(c) for c in cols]
    # analysis: allow(host-sync, header-sync folds K QCs into ONE aggregate
    # check and needs its single boolean now — the intended sync point)
    ok = np.asarray(_multi_pairing_xla(*arrays, jnp.asarray(valid)))
    return bool(ok[0])


def host_multi_pairing_check(pairs) -> bool:
    """Bit-identical host fallback: ONE reference Miller product + ONE
    final exponentiation (ref.pairing_check over the same pair list)."""
    return ref.pairing_check(list(pairs))


def hash_to_g2(msg: bytes):
    """Hash-to-curve entry point (host half of the split — SHA-256
    expansion and cofactor clearing have no batch structure worth a
    kernel; the per-quorum message is hashed once and cached)."""
    return ref.hash_to_g2(msg)


# -- progaudit shape spec: lane bucket 4 (multi_pairing_pad's power-of-two
# ladder). slow: the Miller loop unrolls to ~100k limb eqns — tracing alone
# is minutes-class, so default audits verify these via baseline coverage
# only; --jaxpr-full / --update-jaxpr-baseline re-trace them.
PROGSPEC = {
    "_pairing_check_xla": {
        "bucket": 4,
        "slow": True,
        "inputs": lambda b: [((b, 24), "uint32")] * 10,
    },
    "_multi_pairing_xla": {
        "bucket": 4,
        "slow": True,
        "inputs": lambda b: [((b, 24), "uint32")] * 6 + [((b,), "bool")],
    },
}
