"""Batch Poseidon on TPU — the SNARK-friendly hash lane.

Same shape discipline as :mod:`fisco_bcos_tpu.ops.keccak`: the host pads a
whole batch into a dense bucketed block tensor plus per-lane block counts,
and ONE jitted program sponges every lane in parallel — the permutation is a
``lax.scan`` over the 65 rounds, multi-block messages scan over block slots
with per-lane masking.

Field arithmetic rides :mod:`fisco_bcos_tpu.ops.limb`'s ``MontField`` (BN254
scalar field < 2^256, so the 16×16-bit limb machinery applies unchanged);
state words live in the Montgomery domain end to end — the host encodes
absorbed chunks once and decodes the single squeezed word once, so no
per-round domain conversions.

Every constant is DERIVED from :mod:`fisco_bcos_tpu.crypto.ref.poseidon`
(Grain LFSR round constants, Cauchy MDS) and re-asserted over plain ints at
import — the ops/bls12_381.py discipline: no transcribed magic tables, and a
corrupted constant fails the import, not a consensus round.

The round scan is UNIFORM: every round computes all three S-boxes and a
per-round flag selects the full-round result or the partial-round one
(state word 0 only). That trades ~2× S-box work for a single compiled scan
body — the same masking trade the keccak absorb loop makes, and on the VPU
the S-box is 3 of the 12 muls a round pays anyway (the MDS mix is 9).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..crypto.ref import poseidon as ref
from . import limb
from .hash_common import bucket_batch
from .limb import LIMBS, const_rows, make_mont_field, rows_to_ints, select

FR = ref.FR
T = ref.T
RATE = ref.RATE
BLOCK_BYTES = ref.BLOCK_BYTES
N_ROUNDS = ref.N_ROUNDS

F = make_mont_field(FR)

# ---------------------------------------------------------------------------
# Derived constant tables (Montgomery domain), asserted against the
# reference derivation over plain ints at import.
# ---------------------------------------------------------------------------

_REF_RC = ref.round_constants()
_REF_MDS = ref.mds_matrix()

assert len(_REF_RC) == N_ROUNDS and all(len(r) == T for r in _REF_RC)
assert all(0 <= c < FR for row in _REF_RC for c in row)
for _i in range(T):
    for _j in range(T):
        # the Cauchy property IS the derivation: M[i][j] = 1/(x_i + y_j)
        assert _REF_MDS[_i][_j] * (_i + T + _j) % FR == 1

# [N_ROUNDS, T, 16] Montgomery-encoded round constants
_RC_MONT = np.stack(
    [np.stack([F.enc(c) for c in row]) for row in _REF_RC]
)
# [T][T] -> [16] Montgomery-encoded MDS entries (host constants)
_MDS_MONT = [[F.enc(_REF_MDS[i][j]) for j in range(T)] for i in range(T)]
# per-round full/partial S-box flag (1 = all words, 0 = word 0 only)
_HALF = ref.R_FULL // 2
_FULL_FLAG = np.array(
    [
        1 if (r < _HALF or r >= _HALF + ref.R_PARTIAL) else 0
        for r in range(N_ROUNDS)
    ],
    dtype=np.uint32,
)

# Montgomery round-trip spot check: decoding the encoded table recovers the
# reference int (guards a silent enc/limb-layout regression)
_rinv = pow(1 << 256, FR - 2, FR)
assert (
    sum(int(_RC_MONT[0, 0, k]) << (16 * k) for k in range(LIMBS)) * _rinv % FR
    == _REF_RC[0][0]
)
del _rinv


def _sbox(x: jax.Array) -> jax.Array:
    """x^5 = (x^2)^2 * x — 2 squarings + 1 mul."""
    x2 = F.sqr(x)
    return F.mul(F.sqr(x2), x)


def _round(state: tuple, rc: jax.Array, full: jax.Array) -> tuple:
    """One Poseidon round over a T-tuple of [16, B] Montgomery words."""
    t = state[0].shape[1]
    s = [
        F.add(state[i], jnp.broadcast_to(rc[i][:, None], (LIMBS, t)))
        for i in range(T)
    ]
    boxed = [_sbox(x) for x in s]
    cond = jnp.broadcast_to(full != 0, (t,))
    s = [boxed[0]] + [select(cond, boxed[i], s[i]) for i in range(1, T)]
    out = []
    for i in range(T):
        acc = F.mul(s[0], const_rows(_MDS_MONT[i][0], t))
        for j in range(1, T):
            acc = F.add(acc, F.mul(s[j], const_rows(_MDS_MONT[i][j], t)))
        out.append(acc)
    return tuple(out)


def permute_lanes(state: tuple) -> tuple:
    """The full permutation: lax.scan over the 65 uniform rounds."""

    def body(st, xs):
        rc, full = xs
        return _round(st, rc, full), None

    state, _ = lax.scan(
        body, state, (jnp.asarray(_RC_MONT), jnp.asarray(_FULL_FLAG))
    )
    return state


@jax.jit
def poseidon_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Sponge over pre-padded, Montgomery-encoded blocks.

    blocks: [B, M, RATE, 16] uint32, nblocks: [B] int32.
    Returns the squeezed word as [16, B] PLAIN-domain limbs.
    """
    bsz, m_max, _rate, _limbs = blocks.shape
    zeros = jnp.zeros((LIMBS, bsz), jnp.uint32)
    state0 = (zeros,) * T

    def absorb(state, xs):
        blk, idx = xs  # blk [RATE, 16, B]
        s = list(state)
        for j in range(RATE):
            s[j] = F.add(s[j], blk[j])
        new = permute_lanes(tuple(s))
        active = idx < nblocks
        return tuple(select(active, n, o) for n, o in zip(new, state)), None

    # one up-front transpose so every absorbed word is a contiguous [16, B]
    state, _ = lax.scan(
        absorb,
        state0,
        (jnp.moveaxis(blocks, 0, -1), jnp.arange(m_max, dtype=jnp.int32)),
    )
    return F.to_plain(state[0])


def pad_poseidon(msgs) -> tuple[np.ndarray, np.ndarray]:
    """Sponge padding + Montgomery encoding for a batch.

    Returns (blocks [B', M, RATE, 16] uint32, nblocks [B'] int32) with BOTH
    dims bucketed like :func:`fisco_bcos_tpu.ops.hash_common.pad_keccak`;
    padding rows are the padded empty message."""
    b_pad = bucket_batch(max(len(msgs), 1))
    nblocks = np.array(
        [len(m) // BLOCK_BYTES + 1 for m in msgs] + [1] * (b_pad - len(msgs)),
        dtype=np.int32,
    )
    m_max = bucket_batch(int(nblocks.max()))
    blocks = np.zeros((b_pad, m_max, RATE, LIMBS), dtype=np.uint32)
    for i, m in enumerate(msgs):
        elems = ref.absorb_elements(m)
        for k, v in enumerate(elems):
            blocks[i, k // RATE, k % RATE] = F.enc(v)
    if b_pad > len(msgs):
        empty = [F.enc(v) for v in ref.absorb_elements(b"")]
        for j in range(RATE):
            blocks[len(msgs) :, 0, j] = empty[j]
    return blocks, nblocks


def poseidon_batch_async(msgs):
    """Dispatch the device batch and defer the sync: () -> [B, 32] uint8."""
    n = len(msgs)
    blocks, nblocks = pad_poseidon(msgs)
    words = poseidon_blocks(jnp.asarray(blocks), jnp.asarray(nblocks))

    def resolve() -> np.ndarray:
        # analysis: allow(host-sync, deferred resolver — the sync happens
        # when the caller RESOLVES the plane future, not at dispatch)
        ints = rows_to_ints(np.asarray(words))
        raw = b"".join(v.to_bytes(32, "big") for v in ints[:n])
        return np.frombuffer(raw, dtype=np.uint8).reshape(n, 32).copy()

    return resolve


def poseidon_batch(msgs) -> np.ndarray:
    """Host convenience: list of bytes -> [B, 32] uint8 digests."""
    from ..observability.device import device_span

    n = len(msgs)
    with device_span("poseidon", n, shape_key=bucket_batch(n)):
        return poseidon_batch_async(msgs)()


# -- progaudit shape spec (analysis/progaudit: canonical audited bucket) -----
PROGSPEC = {
    "poseidon_blocks": {
        "bucket": 256,
        "inputs": lambda b: [
            ((b, 1, RATE, 16), "uint32"), ((b,), "int32"),
        ],
    },
}
