"""Batched device kernels — the TPU "crypto & state math plane".

These own every batchable hot loop the reference runs on CPU threads
(SURVEY.md §3: txpool batch verify, PBFT sealer-signature quorum check,
state-root XOR hash, merkle builds). Everything here is jit-compatible,
batch-leading, static-shape JAX.
"""
