"""Vectorized wide merkle trees on the batch hash kernels.

Reference counterpart: bcos-crypto/bcos-crypto/merkle/Merkle.h:35-230 (templated
on hasher and width, default width 16; `generateMerkle` / `generateMerkleProof`
/ `verifyMerkleProof`) and the 2.x parallel variant
bcos-protocol/ParallelMerkleProof.cpp:32-100 (tbb::parallel_for). Used for a
block's transaction/receipt roots (bcos-ledger merkle proofs) — 10k+ leaves per
block at the reference's headline TPS.

TPU formulation: a level with L nodes is one fixed-row-length batch hash —
group up to `width` child digests, concatenate (short groups keep their true
byte length, matching a variable-arity last group), hash all groups in one
device call. The whole tree is O(log_width N) device calls of shrinking batch
size instead of N sequential hashes.

Proofs follow the reference's wide-proof shape: per level, the full child
group of the target node (the verifier re-hashes the group and ascends).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .keccak import keccak256_batch_async, keccak256_blocks
from .sha256 import sha256_batch_async
from .sm3 import sm3_batch_async

HashBatchFn = Callable[[Sequence[bytes]], np.ndarray]

# span-LESS async entries, resolved eagerly: the per-level hash calls run
# inside the enclosing merkle device_span (merkle_root / the plane's
# merkle_tree executor) — a nested per-level hash span would book the same
# wall twice and misfile a cold hash-program compile as merkle execute
# remainder (same reasoning as sm2_e_batch)
def _poseidon_batch(msgs: Sequence[bytes]) -> np.ndarray:
    # lazy: deriving the Grain/Cauchy constant tables costs ~0.2 s at
    # ops.poseidon import, and only the succinct state plane pays it
    from .poseidon import poseidon_batch_async

    return poseidon_batch_async(msgs)()


_HASHERS: dict[str, HashBatchFn] = {
    "keccak256": lambda msgs: keccak256_batch_async(msgs)(),
    "sm3": lambda msgs: sm3_batch_async(msgs)(),
    "sha256": lambda msgs: sha256_batch_async(msgs)(),
    "poseidon": _poseidon_batch,
}


def _host_hash(hasher: str, data: bytes) -> bytes:
    """Single-item host-side hash (native C when available) — the root
    binding is one tiny hash; a device batch call for it would cost a full
    tunnel round trip."""
    from .. import native_bind

    if hasher not in _HASHERS:
        # same rejection the device route gets from its dict lookup — an
        # unknown name must never silently fall through to sha256 (one
        # node raising while another silently hashes is a divergence)
        raise KeyError(hasher)
    if hasher == "keccak256":
        from ..crypto.ref.keccak import keccak256 as ref

        return native_bind.keccak256(data) or ref(data)
    if hasher == "sm3":
        from ..crypto.ref.sm3 import sm3 as ref

        return native_bind.sm3(data) or ref(data)
    if hasher == "poseidon":
        # no native core: the pure-Python reference IS the host path (bit-
        # identical to the jitted sponge by the ops/poseidon.py import pin)
        from ..crypto.ref.poseidon import poseidon_hash as ref_poseidon

        return ref_poseidon(data)
    from ..crypto.ref.sha2 import sha256 as ref

    return native_bind.sha256(data) or ref(data)


def bucket_leaves(n: int) -> int:
    """Leaf-count bucket: every tree is built over a padded size (zero-digest
    filler leaves) so the fused device program compiles once per bucket
    instead of once per distinct block size — a production chain with
    variable block sizes would otherwise recompile the multi-minute tree
    program continuously (r3/r4 advisor churn note).

    Buckets are 5-bit-mantissa floats: the smallest m·2^j ≥ n with
    16 ≤ m ≤ 32. Padding overhead is ≤ 1/16 (vs up to 2× for plain
    power-of-two buckets — the 10k-leaf headline tree pads to 10,240, not
    16,384) while a whole octave of block sizes still shares ≤ 16 compiled
    programs. ≤16 leaves keep their exact size (single-group trees)."""
    if n <= 16:
        return n
    j = n.bit_length() - 5
    return -(-n // (1 << j)) << j


def bind_root(padded_root: bytes, n: int, hasher: str = "keccak256") -> bytes:
    """Final root = H(padded_root ‖ u64(n)). Binding the REAL leaf count
    makes trees of different n in the same bucket (whose padded trees could
    otherwise alias via trailing zero leaves) distinct, and gives single-leaf
    trees leaf≠root domain separation."""
    return _host_hash(hasher, bytes(padded_root) + int(n).to_bytes(8, "big"))


def _prefer_host_tree() -> bool:
    """True when tree levels should be hashed by the native C loop instead
    of a device batch program: on a CPU-only jax backend the XLA keccak
    program costs ~70 ms per 600-leaf root (measured, flood profile r5)
    while the sequential native loop is ~20x faster — the same
    backend-aware routing admit_batch applies to EC. Device backends keep
    the fused device tree (leaves are usually already device-resident)."""
    from .. import native_bind
    from ..crypto.suite import device_backend_is_cpu

    return device_backend_is_cpu() and native_bind.load() is not None


def _host_hash_batch(hasher: str) -> HashBatchFn:
    """Sequential native-C hash_batch with the exact grouping/output shape
    of the device batch fns — roots stay bit-identical across routes."""

    def hb(groups: Sequence[bytes]) -> np.ndarray:
        return np.frombuffer(
            b"".join(_host_hash(hasher, g) for g in groups), dtype=np.uint8
        ).reshape(len(groups), 32).copy()

    return hb


@dataclass(frozen=True)
class MerkleProofItem:
    """One level of a wide merkle proof: the child group containing the
    target, plus the target's index within the group."""

    group: tuple[bytes, ...]
    index: int


def _levels(leaves: np.ndarray, width: int, hash_batch: HashBatchFn) -> list[np.ndarray]:
    """All tree levels bottom-up; level 0 = leaves, last = [1, 32] root."""
    levels = [leaves]
    cur = leaves
    while len(cur) > 1:
        n = len(cur)
        groups = [
            bytes(cur[i : i + width].reshape(-1)) for i in range(0, n, width)
        ]
        cur = hash_batch(groups)
        levels.append(cur)
    return levels


class MerkleTree:
    """Wide merkle tree over 32-byte leaf hashes.

    `leaves` is a [N, 32] uint8 array (already-hashed items, e.g. tx hashes —
    the reference also trees over hashes, Merkle.h:43).
    """

    def __init__(self, leaves: np.ndarray, width: int = 16, hasher: str = "keccak256"):
        leaves = np.asarray(leaves, dtype=np.uint8)
        if leaves.ndim != 2 or leaves.shape[1] != 32:
            raise ValueError("leaves must be [N, 32] uint8")
        if len(leaves) == 0:
            raise ValueError("merkle tree needs at least one leaf")
        if width < 2:
            raise ValueError("width must be >= 2")
        self.width = width
        self.hasher = hasher
        self.n = len(leaves)
        b = bucket_leaves(self.n)
        if b > self.n:  # zero-digest filler up to the bucket (see bucket_leaves)
            leaves = np.vstack([leaves, np.zeros((b - self.n, 32), np.uint8)])
        self._hash_batch = (
            _host_hash_batch(hasher) if _prefer_host_tree() else _HASHERS[hasher]
        )
        self.levels = _levels(leaves, width, self._hash_batch)

    @property
    def padded_root(self) -> bytes:
        """Root of the bucket-padded tree (what the device programs emit)."""
        return bytes(self.levels[-1][0])

    @property
    def root(self) -> bytes:
        return bind_root(self.padded_root, self.n, self.hasher)

    def proof(self, leaf_index: int) -> list[MerkleProofItem]:
        """Proof for leaf `leaf_index`: one child group per level below root."""
        if not 0 <= leaf_index < self.n:
            raise IndexError("leaf index out of range")
        items: list[MerkleProofItem] = []
        idx = leaf_index
        for level in self.levels[:-1]:
            g0 = (idx // self.width) * self.width
            group = tuple(bytes(h) for h in level[g0 : g0 + self.width])
            items.append(MerkleProofItem(group=group, index=idx - g0))
            idx //= self.width
        return items

    @staticmethod
    def verify_proof(
        leaf: bytes,
        leaf_index: int,
        n_leaves: int,
        proof: list[MerkleProofItem],
        root: bytes,
        width: int = 16,
        hasher: str = "keccak256",
    ) -> bool:
        """Recompute the path from a *positioned* leaf up to `root`.

        Binding to (leaf_index, n_leaves) pins the proof depth and every
        group's size/offset — without it, a truncated proof could certify an
        internal digest as a leaf (no leaf/inner domain separation exists in
        the reference's digest-over-digests scheme either, Merkle.h:43; the
        verifier there likewise knows the leaf count from the block header).
        """
        if not 0 <= leaf_index < n_leaves:
            return False
        if len(leaf) != 32:
            return False
        cur = leaf
        # the tree is built over the bucket-padded leaf set; group sizes and
        # depth follow the PADDED size, the final binding hash pins the REAL n
        idx, size = leaf_index, bucket_leaves(n_leaves)
        for item in proof:
            if size <= 1:
                return False  # proof longer than the tree is deep
            g0 = (idx // width) * width
            if item.index != idx - g0:
                return False
            if len(item.group) != min(width, size - g0):
                return False
            # every entry must be a digest: without this, a repartition of the
            # same concatenated bytes forges membership of a 32-byte window
            # straddling two real digests
            if any(len(h) != 32 for h in item.group):
                return False
            if item.group[item.index] != cur:
                return False
            # one tiny hash per level: host-side always (a device batch of
            # size 1 would cost a full tunnel round trip — same reasoning
            # as bind_root; bit-identical to the device kernels)
            cur = _host_hash(hasher, b"".join(item.group))
            idx //= width
            size = -(-size // width)
        if size != 1:
            return False  # proof shorter than the tree is deep
        return bind_root(cur, n_leaves, hasher) == root


# ---------------------------------------------------------------------------
# Fused device tree (root-only hot path)
# ---------------------------------------------------------------------------
#
# The generic MerkleTree path above does one host round trip per level with
# Python per-group byte packing — fine for proofs and small blocks, but on a
# tunneled TPU every device sync is a network round trip, so a 10k-leaf root
# cost ~4 syncs + host loops (~350 ms measured). The fused path packs keccak
# sponge blocks with pure jnp reshapes and runs ALL levels in one jitted
# device program: one transfer in, 32 bytes out. Bit-identical to the host
# path (same grouping, same short-last-group semantics).

_LANES = 17  # keccak rate 136 bytes = 17 64-bit lanes


def _group_pad_const(msg_len: int, m_pad: int) -> np.ndarray:
    """Keccak 0x01..0x80 multi-rate padding bytes for a msg_len-byte group,
    zero-extended so every group occupies m_pad sponge blocks."""
    pad = np.zeros(m_pad * 136 - msg_len, dtype=np.uint8)
    padlen = (msg_len // 136 + 1) * 136 - msg_len
    if padlen == 1:
        pad[0] = 0x81
    else:
        pad[0] = 0x01
        pad[padlen - 1] |= 0x80
    return pad


def _bytes_to_lanes(buf, m: int):
    """[B, m*136] uint8 -> [B, m, 17, 2] uint32 little-endian lo/hi."""
    b = buf.reshape(buf.shape[0], m, _LANES, 2, 4).astype(jnp.uint32)
    return (
        b[..., 0]
        | (b[..., 1] << 8)
        | (b[..., 2] << 16)
        | (b[..., 3] << 24)
    )


def _words_to_bytes(words):
    """[B, 8] uint32 LE digest words -> [B, 32] uint8 (device)."""
    by = jnp.stack(
        [(words >> (8 * k)) & 0xFF for k in range(4)], axis=-1
    )  # [B, 8, 4]
    return by.reshape(words.shape[0], 32).astype(jnp.uint8)


# analysis: allow(shape-bucket) — runs INSIDE jit traces whose leaf count was
# already padded to bucket_leaves by _device_root_fn's callers
def _device_level(cur, width: int):
    """One tree level on device: [L, 32] uint8 -> [ceil(L/width), 32]."""
    L = cur.shape[0]
    gfull, rem = divmod(L, width)
    m_pad = (width * 32) // 136 + 1  # blocks per full group (4 at width 16)
    bufs = []
    nblocks = []
    if gfull:
        full = cur[: gfull * width].reshape(gfull, width * 32)
        pad = jnp.broadcast_to(
            jnp.asarray(_group_pad_const(width * 32, m_pad)), (gfull, m_pad * 136 - width * 32)
        )
        bufs.append(jnp.concatenate([full, pad], axis=1))
        nblocks += [width * 32 // 136 + 1] * gfull
    if rem:
        msg = rem * 32
        tail = cur[gfull * width :].reshape(1, msg)
        pad = jnp.asarray(_group_pad_const(msg, m_pad))[None]
        bufs.append(jnp.concatenate([tail, pad], axis=1))
        nblocks.append(msg // 136 + 1)
    buf = bufs[0] if len(bufs) == 1 else jnp.concatenate(bufs, axis=0)
    lanes = _bytes_to_lanes(buf, m_pad)
    words = keccak256_blocks(lanes, jnp.asarray(np.array(nblocks, np.int32)))
    return _words_to_bytes(words)


@lru_cache(maxsize=64)
def _device_root_fn(n: int, width: int):
    @jax.jit
    def run(leaves):
        cur = leaves
        while cur.shape[0] > 1:
            cur = _device_level(cur, width)
        return cur[0]

    return run


def merkle_root_async(
    leaves: np.ndarray, width: int = 16, hasher: str = "keccak256"
):
    """Dispatch the root computation, defer the device sync: () -> bytes.

    Large keccak trees dispatch the fused single-program device path and
    resolve on call (letting the sealing path queue tx root, receipts root
    and state root before paying any device round trip); proofs, small
    trees and other hashers compute eagerly inside this call."""
    from ..observability.device import device_span

    if not isinstance(leaves, jax.Array):
        leaves = np.asarray(leaves, dtype=np.uint8)
    # same validation whichever path runs (MerkleTree re-checks on its path)
    if leaves.ndim != 2 or leaves.shape[1] != 32:
        raise ValueError("leaves must be [N, 32] uint8")
    if width < 2:
        raise ValueError("width must be >= 2")
    # the span lives HERE (not in the merkle_root sync wrapper) so the
    # sealing path's suite.merkle_root_async calls are attributed too; it
    # covers the dispatch only — the resolver's sync is the caller's wait,
    # same contract as the hash-plane executor
    n = len(leaves)
    key = (hasher, width, bucket_leaves(max(n, 1)))
    with device_span("merkle_root", n, shape_key=key):
        if (
            hasher == "keccak256"
            and len(leaves) >= 256
            and not _prefer_host_tree()
        ):
            # jax.Array input stays on device — tx/receipt hashes come from
            # the batch hash kernels, so the hot sealing path never
            # round-trips the leaf tensor through the host. Padding to the
            # leaf-count bucket happens OUTSIDE the jit so the tree
            # program's input shape (and hence its compilation) is shared
            # by every block size in the bucket.
            b = bucket_leaves(n)
            arr = jnp.asarray(leaves).astype(jnp.uint8)
            if b > n:
                arr = jnp.concatenate([arr, jnp.zeros((b - n, 32), jnp.uint8)])
            dev = _device_root_fn(b, width)(arr)
            return lambda: bind_root(bytes(np.asarray(dev)), n, hasher)
        root = MerkleTree(
            np.asarray(leaves, dtype=np.uint8), width=width, hasher=hasher
        ).root
        return lambda: root


def merkle_root(
    leaves: np.ndarray, width: int = 16, hasher: str = "keccak256"
) -> bytes:
    """Root only (the hot path for block sealing: tx/receipt roots).
    The device_span lives in :func:`merkle_root_async` — a second one here
    would double-count the dispatch."""
    return merkle_root_async(leaves, width=width, hasher=hasher)()


# -- progaudit shape spec: the root program is a maker product — audit the
# width-16 keccak tree at one ladder leaf count.
PROGSPEC = {
    "_device_root_fn.run": {
        "bucket": 256,
        "call": lambda b: _device_root_fn(b, 16),
        "inputs": lambda b: [((b, 32), "uint8")],
    },
}
