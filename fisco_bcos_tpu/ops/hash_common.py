"""Host-side batch padding for the device hash kernels.

The reference hashes one message at a time on CPU threads (OpenSSL EVP behind
bcos-crypto's Hash interface, tbb::parallel_for for batches). The TPU
formulation pads a whole batch into a dense ``[B, M, words]`` block tensor plus
a per-lane block count; the device kernel scans over the M block slots and
masks inactive lanes. M is rounded up to a bounded shape schedule (powers of two, then multiples of
2048) to bound the number of
distinct compiled shapes (XLA needs static shapes).
"""

from __future__ import annotations

import os
from collections.abc import Sequence

import numpy as np


def _bucket(n: int) -> int:
    """Round up to a bounded set of batch shapes to limit recompilation:
    powers of two up to 2048, then multiples of 2048 (a 10k-tx block pads to
    10240 lanes, not 16384 — padding waste stays under 2%).

    FISCO_TEST_BUCKET=<q> (set by tests/conftest.py) quantizes every batch to
    multiples of q instead, so the whole CPU test suite shares one or two
    compiled shapes — XLA compiles of the big EC programs dominate test
    wall-time otherwise (VERDICT r1 weak #3)."""
    q = int(os.environ.get("FISCO_TEST_BUCKET", "0"))
    if q:
        return max(q, -(-n // q) * q)
    if n <= 2048:
        m = 1
        while m < n:
            m *= 2
        return m
    return -(-n // 2048) * 2048


bucket_batch = _bucket  # shared by the EC kernels' host wrappers


def bucket_ladder(max_n: int) -> list[int]:
    """Every bucket :func:`_bucket` can produce for batches up to ``max_n``
    — i.e. the maximum number of distinct compiled batch shapes a flood of
    arbitrary sizes ≤ max_n can force per op. tool/check_device_plane.py
    asserts the live compile counter against ``len(bucket_ladder(...))``;
    honors FISCO_TEST_BUCKET quantization like _bucket itself."""
    max_n = max(int(max_n), 1)
    ladder: list[int] = []
    n = 1
    while True:
        b = _bucket(n)
        if not ladder or b != ladder[-1]:
            ladder.append(b)
        if b >= max_n:
            return ladder
        n = b + 1


def pad_rows(a: np.ndarray, rows: int) -> np.ndarray:
    """Zero-pad a batch array along axis 0 to `rows` (bucketed batch sizes)."""
    if a.shape[0] == rows:
        return a
    pad = np.zeros((rows - a.shape[0],) + a.shape[1:], dtype=a.dtype)
    return np.concatenate([a, pad], axis=0)


def pad_keccak(
    msgs: Sequence[bytes], rate: int = 136
) -> tuple[np.ndarray, np.ndarray]:
    """Keccak multi-rate padding (0x01 … 0x80 legacy domain).

    Returns (blocks [B', M, rate//8, 2] uint32 little-endian lo/hi lane
    halves, nblocks [B'] int32), where B' = _bucket(len(msgs)): BOTH dims
    are bucketed so one compiled program serves a whole octave of batch
    sizes — the state-root/tx-hash paths otherwise recompile per distinct
    dirty-set size (r5 flood profile). Padding rows are empty messages;
    callers that need exactly len(msgs) digests slice the result (the
    *_batch_async resolvers do).
    """
    b_pad = _bucket(max(len(msgs), 1))
    nblocks = np.array(
        [len(m) // rate + 1 for m in msgs] + [1] * (b_pad - len(msgs)),
        dtype=np.int32,
    )
    m_max = _bucket(int(nblocks.max()))
    lanes = rate // 8
    buf = np.zeros((b_pad, m_max * rate), dtype=np.uint8)
    for i, m in enumerate(msgs):
        buf[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        end = nblocks[i] * rate
        buf[i, len(m)] ^= 0x01
        buf[i, end - 1] ^= 0x80
    if b_pad > len(msgs):  # all pad rows are the padded empty message
        buf[len(msgs):, 0] = 0x01
        buf[len(msgs):, rate - 1] = 0x80
    words = buf.view("<u4").reshape(b_pad, m_max, lanes, 2)
    return words.astype(np.uint32), nblocks


def pad_md64(
    msgs: Sequence[bytes],
) -> tuple[np.ndarray, np.ndarray]:
    """Merkle–Damgård padding with 64-bit big-endian length (SHA-256 and SM3
    share it): 0x80, zeros, bitlen. Returns (blocks [B', M, 16] uint32
    big-endian words, nblocks [B'] int32); B' = _bucket(len(msgs)) with
    empty-message padding rows, exactly like :func:`pad_keccak`."""
    b_pad = _bucket(max(len(msgs), 1))
    nblocks = np.array(
        [(len(m) + 8) // 64 + 1 for m in msgs] + [1] * (b_pad - len(msgs)),
        dtype=np.int32,
    )
    m_max = _bucket(int(nblocks.max()))
    buf = np.zeros((b_pad, m_max * 64), dtype=np.uint8)
    for i, m in enumerate(msgs):
        buf[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        buf[i, len(m)] = 0x80
        end = nblocks[i] * 64
        buf[i, end - 8 : end] = np.frombuffer(
            (len(m) * 8).to_bytes(8, "big"), dtype=np.uint8
        )
    if b_pad > len(msgs):  # pad rows: empty message = 0x80 + zero bitlen
        buf[len(msgs):, 0] = 0x80
    words = buf.view(">u4").reshape(b_pad, m_max, 16)
    return words.astype(np.uint32), nblocks


def digest_words_to_bytes_le(words: np.ndarray) -> np.ndarray:
    """[B, 8] uint32 little-endian words -> [B, 32] uint8 (keccak digests)."""
    return np.ascontiguousarray(np.asarray(words, dtype="<u4")).view(np.uint8).reshape(
        *words.shape[:-1], 32
    )


def digest_words_to_bytes_be(words: np.ndarray) -> np.ndarray:
    """[B, 8] uint32 big-endian words -> [B, 32] uint8 (sha256/sm3 digests)."""
    return (
        np.ascontiguousarray(np.asarray(words, dtype=np.uint32).astype(">u4"))
        .view(np.uint8)
        .reshape(*words.shape[:-1], 32)
    )
