"""Device-side sender-address derivation.

The reference computes the tx sender as right160(keccak256(uncompressed
pubkey)) on CPU after each single-signature recover (CryptoSuite.h:56-59,
``calculateAddress``; called from ``Transaction::verify()``
bcos-framework/bcos-framework/protocol/Transaction.h:64-84). Here the whole
batch of recovered pubkeys is hashed in one fused device program — a 64-byte
message plus keccak padding fits a single rate block, so ``nblocks`` is 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .bigint import limbs_to_bytes_device
from .keccak import keccak256_blocks

_RATE_BYTES = 136
_RATE_LANES = 17


def _bytes_to_blocks(msg_bytes: jax.Array) -> jax.Array:
    """[B, 136] uint32 byte values -> [B, 1, 17, 2] uint32 lane halves (the
    block tensor layout keccak256_blocks consumes)."""
    b = msg_bytes.astype(jnp.uint32).reshape(-1, 2 * _RATE_LANES, 4)
    w = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)
    return jnp.stack([w[..., 0::2], w[..., 1::2]], axis=-1)[:, None, :, :]


@jax.jit
def sender_address_device(qx: jax.Array, qy: jax.Array) -> jax.Array:
    """Batch address derivation: affine pubkey limbs ([B, 16] each, plain
    domain) -> [B, 20] uint32 address byte values.

    address = keccak256(qx_be32 ‖ qy_be32)[12:32]; multi-rate padding
    (0x01 at byte 64, 0x80 at byte 135) is applied inline.
    """
    batch = qx.shape[0]
    msg = jnp.zeros((batch, _RATE_BYTES), jnp.uint32)
    msg = msg.at[:, 0:32].set(limbs_to_bytes_device(qx))
    msg = msg.at[:, 32:64].set(limbs_to_bytes_device(qy))
    msg = msg.at[:, 64].set(0x01)
    msg = msg.at[:, 135].set(0x80)
    words = keccak256_blocks(
        _bytes_to_blocks(msg), jnp.ones((batch,), jnp.int32)
    )  # [B, 8] little-endian digest words
    idx = jnp.arange(12, 32)
    return (words[:, idx // 4] >> (8 * (idx % 4))) & 0xFF


# -- progaudit shape spec (analysis/progaudit: canonical audited bucket) -----
PROGSPEC = {
    "sender_address_device": {
        "bucket": 256,
        "inputs": lambda b: [((b, 16), "uint32")] * 2,
    },
}
