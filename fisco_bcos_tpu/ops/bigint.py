"""Vectorized 256-bit modular arithmetic for TPU (uint32 lanes).

Replaces the reference's CPU bignum (wedpr-crypto Rust FFI / OpenSSL BN used by
bcos-crypto's secp256k1/SM2 paths) with a batch formulation XLA can fuse:

- A 256-bit number is 16 little-endian 16-bit limbs stored in a uint32 array of
  shape ``[..., 16]`` (leading dims are the batch). 16-bit limbs keep every
  partial product (≤ (2^16-1)^2) and every column sum inside uint32 — TPUs have
  no native 64-bit integer path worth using.
- Products are computed as one batched outer product (``[..., 16, 16]``) and
  accumulated along anti-diagonals; carry propagation is a short
  ``lax.scan`` along the limb axis (sequential over 32 limbs, vectorized over
  the batch — the batch is where the parallelism lives).
- Modular reduction is full-word Montgomery (REDC with R = 2^256), uniform for
  any odd modulus, so secp256k1's p/n and SM2's p/n share one code path.

All entry points are jit-safe, shape-polymorphic in the batch dims, and use no
data-dependent control flow (selects only) — consensus-critical code must be
constant-shape and branch-free on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

LIMBS = 16  # 16 x 16-bit limbs = 256 bits
_MASK = jnp.uint32(0xFFFF)
_R = 1 << 256


# ---------------------------------------------------------------------------
# Host-side conversions (numpy, exact Python ints)
# ---------------------------------------------------------------------------


def int_to_limbs(x: int) -> np.ndarray:
    """Python int -> [16] uint32 little-endian 16-bit limbs."""
    if not 0 <= x < _R:
        raise ValueError("int_to_limbs: out of range")
    return np.array([(x >> (16 * i)) & 0xFFFF for i in range(LIMBS)], dtype=np.uint32)


def limbs_to_int(a) -> int:
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(a[..., i]) << (16 * i) for i in range(a.shape[-1]))


def ints_to_limbs(xs) -> np.ndarray:
    """Iterable of ints -> [B, 16] uint32."""
    return np.stack([int_to_limbs(int(x)) for x in xs])


def limbs_to_ints(arr) -> list[int]:
    arr = np.asarray(arr)
    flat = arr.reshape(-1, arr.shape[-1])
    return [sum(int(row[i]) << (16 * i) for i in range(arr.shape[-1])) for row in flat]


def bytes_be_to_limbs(data: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 big-endian byte rows -> [B, 16] uint32 limbs (vectorized)."""
    data = np.asarray(data, dtype=np.uint8)
    pairs = data.reshape(*data.shape[:-1], 16, 2).astype(np.uint32)
    be16 = pairs[..., 0] * 256 + pairs[..., 1]
    return be16[..., ::-1].copy()


def limbs_to_bytes_be(limbs: np.ndarray) -> np.ndarray:
    """[B, 16] uint32 limbs -> [B, 32] uint8 big-endian byte rows."""
    limbs = np.asarray(limbs, dtype=np.uint32)[..., ::-1]
    hi = (limbs >> 8).astype(np.uint8)
    lo = (limbs & 0xFF).astype(np.uint8)
    return np.stack([hi, lo], axis=-1).reshape(*limbs.shape[:-1], 32)


# ---------------------------------------------------------------------------
# Device-side digest-word -> limb conversion (keeps hash -> EC pipelines fused
# on device; the reference round-trips through CPU byte buffers instead)
# ---------------------------------------------------------------------------


def _bswap32(w: jax.Array) -> jax.Array:
    w = w.astype(jnp.uint32)
    return ((w & 0xFF) << 24) | ((w & 0xFF00) << 8) | ((w >> 8) & 0xFF00) | (w >> 24)


def _chunks32_be_to_limbs(chunks: jax.Array) -> jax.Array:
    """[..., 8] uint32 big-endian-ordered 32-bit chunks -> [..., 16] limbs."""
    rc = chunks[..., ::-1]  # chunk 7 holds the least-significant 32 bits
    lo = rc & 0xFFFF
    hi = rc >> 16
    return jnp.stack([lo, hi], axis=-1).reshape(*chunks.shape[:-1], LIMBS)


def digest_words_le_to_limbs(words: jax.Array) -> jax.Array:
    """Keccak digest words ([..., 8] uint32 little-endian byte order, digest
    read as a big-endian 256-bit integer) -> [..., 16] limbs, on device."""
    return _chunks32_be_to_limbs(_bswap32(words))


def digest_words_be_to_limbs(words: jax.Array) -> jax.Array:
    """SHA-256/SM3 digest words ([..., 8] uint32 big-endian) -> [..., 16] limbs."""
    return _chunks32_be_to_limbs(words.astype(jnp.uint32))


def limbs_to_bytes_device(limbs: jax.Array) -> jax.Array:
    """[..., 16] limbs -> [..., 32] big-endian bytes (uint32 lanes), on device."""
    rev = limbs[..., ::-1].astype(jnp.uint32)
    hi = rev >> 8
    lo = rev & 0xFF
    return jnp.stack([hi, lo], axis=-1).reshape(*limbs.shape[:-1], 32)


# ---------------------------------------------------------------------------
# Modulus context (host-precomputed Montgomery constants)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Modulus:
    """Montgomery context for an odd modulus m < 2^256 (device constants)."""

    m_int: int
    limbs: np.ndarray = field(repr=False)  # [16] m
    mprime: np.ndarray = field(repr=False)  # [16] -m^-1 mod 2^256
    r1: np.ndarray = field(repr=False)  # [16] R mod m   (Montgomery one)
    r2: np.ndarray = field(repr=False)  # [16] R^2 mod m (to-Montgomery factor)

    def __hash__(self):
        return hash(self.m_int)

    def __eq__(self, other):
        return isinstance(other, Modulus) and self.m_int == other.m_int


def make_modulus(m: int) -> Modulus:
    if m % 2 == 0 or not 2 < m < _R:
        raise ValueError("modulus must be odd and < 2^256")
    mprime = (-pow(m, -1, _R)) % _R
    return Modulus(
        m_int=m,
        limbs=int_to_limbs(m),
        mprime=int_to_limbs(mprime),
        r1=int_to_limbs(_R % m),
        r2=int_to_limbs((_R * _R) % m),
    )


# ---------------------------------------------------------------------------
# Carry machinery (lax.scan along the limb axis, batch-vectorized)
# ---------------------------------------------------------------------------


# Carries are a carry-lookahead problem, not a sequential one: a 32-step
# lax.scan per normalization made every mont_mul ~130 sequential device steps
# (the throughput ceiling of the whole EC plane). Instead: one split pass
# reduces arbitrary column sums to "limbs + {0,1} increments", and the
# remaining binary carry chain is Kogge-Stone — generate/propagate pairs
# combined with lax.associative_scan in log2(L) depth.


def _gp_combine(x, y):
    """(generate, propagate) composition — associative."""
    gx, px = x
    gy, py = y
    return gy | (py & gx), py & px


def _ks_carry_in(g: jax.Array, p: jax.Array) -> jax.Array:
    """Carry INTO each position given per-position generate/propagate."""
    G, _ = lax.associative_scan(_gp_combine, (g, p), axis=-1)
    return jnp.concatenate([jnp.zeros_like(G[..., :1]), G[..., :-1]], axis=-1)


def _shift_up(x: jax.Array) -> jax.Array:
    """[..., L] -> [..., L] shifted one limb toward the high end."""
    return jnp.concatenate([jnp.zeros_like(x[..., :1]), x[..., :-1]], axis=-1)


def _carry_normalize(cols: jax.Array) -> jax.Array:
    """Propagate carries: [..., L] uint32 column sums (< 2^27) -> [..., L+1]
    normalized 16-bit limbs (the extra limb is the final carry-out)."""
    cols = jnp.concatenate([cols, jnp.zeros_like(cols[..., :1])], axis=-1)
    s = (cols & _MASK) + _shift_up(cols >> 16)  # < 2^16 + 2^11 < 2^17
    t = (s & _MASK) + _shift_up(s >> 16)  # ≤ 2^16 (increments are {0,1})
    g = t > _MASK
    p = t == _MASK
    return (t + _ks_carry_in(g, p).astype(jnp.uint32)) & _MASK


def _sub_with_borrow(a: jax.Array, b: jax.Array):
    """(a - b) limbwise -> (diff [..., L] normalized, borrow_out [...] in {0,1})."""
    g = a < b  # borrow generated regardless of incoming borrow
    p = a == b  # incoming borrow propagates
    G, _ = lax.associative_scan(_gp_combine, (g, p), axis=-1)
    bin_ = jnp.concatenate([jnp.zeros_like(G[..., :1]), G[..., :-1]], axis=-1)
    diff = (a + jnp.uint32(0x10000) - b - bin_.astype(jnp.uint32)) & _MASK
    return diff, G[..., -1].astype(jnp.uint32)


def _add_raw(a: jax.Array, b: jax.Array) -> jax.Array:
    """Exact limbwise add of two normalized arrays -> [..., L+1] normalized."""
    return _carry_normalize(a + b)


# ---------------------------------------------------------------------------
# Multiplication (batched outer product + anti-diagonal accumulation)
# ---------------------------------------------------------------------------


_DIAG_CACHE: dict = {}


def _diag_mats(out_limbs: int):
    """Constant 0/1 matrices turning the flattened outer product into column
    sums: lo16 parts land in column i+j, hi16 parts in column i+j+1. Shapes
    [256, out_limbs] — the accumulation becomes one integer matmul per part,
    which XLA maps onto the MXU instead of a serial scatter chain."""
    key = out_limbs
    if key not in _DIAG_CACHE:
        a_lo = np.zeros((LIMBS * LIMBS, out_limbs), dtype=np.int32)
        a_hi = np.zeros((LIMBS * LIMBS, out_limbs), dtype=np.int32)
        for i in range(LIMBS):
            for j in range(LIMBS):
                if i + j < out_limbs:
                    a_lo[i * LIMBS + j, i + j] = 1
                if i + j + 1 < out_limbs:
                    a_hi[i * LIMBS + j, i + j + 1] = 1
        _DIAG_CACHE[key] = (a_lo, a_hi)
    return _DIAG_CACHE[key]


def _mul_columns(a: jax.Array, b: jax.Array, out_limbs: int) -> jax.Array:
    """Column sums of a*b: [..., 16] x [..., 16] -> [..., out_limbs] raw columns.

    Column k collects lo16(a_i*b_j) for i+j=k and hi16 for i+j=k-1; every
    column sum is < 32 * 2^16 + 2^16 < 2^22, well inside int32/uint32.
    """
    prod = a[..., :, None] * b[..., None, :]  # [..., 16, 16] — each < 2^32 ✔
    lo = (prod & _MASK).astype(jnp.int32).reshape(a.shape[:-1] + (LIMBS * LIMBS,))
    hi = (prod >> 16).astype(jnp.int32).reshape(a.shape[:-1] + (LIMBS * LIMBS,))
    a_lo, a_hi = _diag_mats(out_limbs)
    cols = lo @ jnp.asarray(a_lo) + hi @ jnp.asarray(a_hi)
    return cols.astype(jnp.uint32)


@jax.jit
def mul_full(a: jax.Array, b: jax.Array) -> jax.Array:
    """Full 256x256 -> 512-bit product: [..., 16] x [..., 16] -> [..., 32]."""
    return _carry_normalize(_mul_columns(a, b, 32))[..., :32]


@jax.jit
def mul_low(a: jax.Array, b: jax.Array) -> jax.Array:
    """Low 256 bits of the product (mod 2^256) -> [..., 16]."""
    return _carry_normalize(_mul_columns(a, b, LIMBS))[..., :LIMBS]


# ---------------------------------------------------------------------------
# Montgomery arithmetic
# ---------------------------------------------------------------------------


def _const(mod_arr: np.ndarray, like: jax.Array) -> jax.Array:
    """Broadcast a [16] host constant across the batch dims of `like`."""
    c = jnp.asarray(mod_arr)
    return jnp.broadcast_to(c, like.shape[:-1] + (LIMBS,))


@partial(jax.jit, static_argnames="mod")
def redc(t: jax.Array, mod: Modulus) -> jax.Array:
    """Montgomery reduction: t [..., 32] (t < m*R) -> t*R^-1 mod m, [..., 16]."""
    t_lo = t[..., :LIMBS]
    m_val = mul_low(t_lo, _const(mod.mprime, t_lo))
    mm = mul_full(m_val, _const(mod.limbs, t_lo))  # [..., 32]
    s = _carry_normalize(t + mm)  # [..., 33]; low 16 limbs are zero
    res17 = s[..., LIMBS:]  # [..., 17] — value < 2m < 2^257
    m17 = jnp.pad(_const(mod.limbs, t_lo), [(0, 0)] * (t_lo.ndim - 1) + [(0, 1)])
    diff, borrow = _sub_with_borrow(res17, m17)
    res = jnp.where((borrow == 0)[..., None], diff, res17)
    return res[..., :LIMBS]


@partial(jax.jit, static_argnames="mod")
def mont_mul(a: jax.Array, b: jax.Array, mod: Modulus) -> jax.Array:
    return redc(mul_full(a, b), mod)


@partial(jax.jit, static_argnames="mod")
def mont_sqr(a: jax.Array, mod: Modulus) -> jax.Array:
    return redc(mul_full(a, a), mod)


@partial(jax.jit, static_argnames="mod")
def to_mont(a: jax.Array, mod: Modulus) -> jax.Array:
    return mont_mul(a, _const(mod.r2, a), mod)


@partial(jax.jit, static_argnames="mod")
def from_mont(a: jax.Array, mod: Modulus) -> jax.Array:
    pad = [(0, 0)] * (a.ndim - 1) + [(0, LIMBS)]
    return redc(jnp.pad(a, pad), mod)


@partial(jax.jit, static_argnames="mod")
def add_mod(a: jax.Array, b: jax.Array, mod: Modulus) -> jax.Array:
    """(a + b) mod m for normalized a, b < m."""
    s = _add_raw(a, b)  # [..., 17]
    m17 = jnp.pad(_const(mod.limbs, a), [(0, 0)] * (a.ndim - 1) + [(0, 1)])
    diff, borrow = _sub_with_borrow(s, m17)
    return jnp.where((borrow == 0)[..., None], diff, s)[..., :LIMBS]


@partial(jax.jit, static_argnames="mod")
def sub_mod(a: jax.Array, b: jax.Array, mod: Modulus) -> jax.Array:
    """(a - b) mod m for normalized a, b < m."""
    diff, borrow = _sub_with_borrow(a, b)
    plus_m = _add_raw(diff, _const(mod.limbs, a))[..., :LIMBS]
    return jnp.where((borrow == 0)[..., None], diff, plus_m)


def is_zero(a: jax.Array) -> jax.Array:
    return jnp.all(a == 0, axis=-1)


def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.all(a == b, axis=-1)


def geq(a: jax.Array, b: jax.Array) -> jax.Array:
    """a >= b elementwise over the batch (normalized limbs)."""
    _, borrow = _sub_with_borrow(a, b)
    return borrow == 0


def select(cond: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """cond [...] -> cond ? a : b over [..., 16] operands."""
    return jnp.where(cond[..., None], a, b)


@partial(jax.jit, static_argnames=("e", "mod"))
def mont_pow(a: jax.Array, e: int, mod: Modulus) -> jax.Array:
    """a^e mod m (a in Montgomery domain, e a fixed Python int exponent).

    MSB-first square-and-multiply via lax.scan over the (static) bit string —
    constant-time across lanes, ~2 mulmods per bit.
    """
    if e == 0:
        return _const(mod.r1, a)
    bits = np.array(
        [(e >> i) & 1 for i in range(e.bit_length() - 1, -1, -1)], dtype=np.uint32
    )
    acc0 = _const(mod.r1, a)

    def step(acc, bit):
        acc = mont_sqr(acc, mod)
        withmul = mont_mul(acc, a, mod)
        return jnp.where((bit != 0), withmul, acc), None

    acc, _ = lax.scan(step, acc0, jnp.asarray(bits))
    return acc


def mont_inv(a: jax.Array, mod: Modulus) -> jax.Array:
    """Modular inverse via Fermat (modulus must be prime). Returns 0 for a=0."""
    return mont_pow(a, mod.m_int - 2, mod)
