"""Host-side 256-bit limb/byte conversions + device digest→limb adapters.

Number layout: a 256-bit value is 16 little-endian 16-bit limbs. Host-side
(numpy) arrays here are **batch-major** ``[B, 16]`` — the stable public
layout of the crypto suite APIs; the device math core
(:mod:`fisco_bcos_tpu.ops.limb`) is **limb-major** ``[16, T]`` for full VPU
lane utilization and transposes at its entry points.

The device-side converters keep hash → EC pipelines fused on device (the
reference round-trips through CPU byte buffers between OpenSSL EVP hashing
and wedpr EC calls instead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

LIMBS = 16  # 16 x 16-bit limbs = 256 bits
_R = 1 << 256


# ---------------------------------------------------------------------------
# Host-side conversions (numpy, exact Python ints)
# ---------------------------------------------------------------------------


def int_to_limbs(x: int) -> np.ndarray:
    """Python int -> [16] uint32 little-endian 16-bit limbs."""
    if not 0 <= x < _R:
        raise ValueError("int_to_limbs: out of range")
    return np.array([(x >> (16 * i)) & 0xFFFF for i in range(LIMBS)], dtype=np.uint32)


def limbs_to_int(a) -> int:
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(a[..., i]) << (16 * i) for i in range(a.shape[-1]))


def ints_to_limbs(xs) -> np.ndarray:
    """Iterable of ints -> [B, 16] uint32."""
    return np.stack([int_to_limbs(int(x)) for x in xs])


def limbs_to_ints(arr) -> list[int]:
    arr = np.asarray(arr)
    flat = arr.reshape(-1, arr.shape[-1])
    return [sum(int(row[i]) << (16 * i) for i in range(arr.shape[-1])) for row in flat]


def bytes_be_to_limbs(data: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 big-endian byte rows -> [B, 16] uint32 limbs (vectorized)."""
    data = np.asarray(data, dtype=np.uint8)
    pairs = data.reshape(*data.shape[:-1], 16, 2).astype(np.uint32)
    be16 = pairs[..., 0] * 256 + pairs[..., 1]
    return be16[..., ::-1].copy()


def limbs_to_bytes_be(limbs: np.ndarray) -> np.ndarray:
    """[B, 16] uint32 limbs -> [B, 32] uint8 big-endian byte rows."""
    limbs = np.asarray(limbs, dtype=np.uint32)[..., ::-1]
    hi = (limbs >> 8).astype(np.uint8)
    lo = (limbs & 0xFF).astype(np.uint8)
    return np.stack([hi, lo], axis=-1).reshape(*limbs.shape[:-1], 32)


# ---------------------------------------------------------------------------
# Device-side digest-word -> limb conversion (keeps hash -> EC pipelines
# fused on device)
# ---------------------------------------------------------------------------


def _bswap32(w: jax.Array) -> jax.Array:
    w = w.astype(jnp.uint32)
    return ((w & 0xFF) << 24) | ((w & 0xFF00) << 8) | ((w >> 8) & 0xFF00) | (w >> 24)


def _chunks32_be_to_limbs(chunks: jax.Array) -> jax.Array:
    """[..., 8] uint32 big-endian-ordered 32-bit chunks -> [..., 16] limbs."""
    rc = chunks[..., ::-1]  # chunk 7 holds the least-significant 32 bits
    lo = rc & 0xFFFF
    hi = rc >> 16
    return jnp.stack([lo, hi], axis=-1).reshape(*chunks.shape[:-1], LIMBS)


def digest_words_le_to_limbs(words: jax.Array) -> jax.Array:
    """Keccak digest words ([..., 8] uint32 little-endian byte order, digest
    read as a big-endian 256-bit integer) -> [..., 16] limbs, on device."""
    return _chunks32_be_to_limbs(_bswap32(words))


def digest_words_be_to_limbs(words: jax.Array) -> jax.Array:
    """SHA-256/SM3 digest words ([..., 8] uint32 big-endian) -> [..., 16] limbs."""
    return _chunks32_be_to_limbs(words.astype(jnp.uint32))


def limbs_to_bytes_device(limbs: jax.Array) -> jax.Array:
    """[..., 16] limbs -> [..., 32] big-endian bytes (uint32 lanes), on device."""
    rev = limbs[..., ::-1].astype(jnp.uint32)
    hi = rev >> 8
    lo = rev & 0xFF
    return jnp.stack([hi, lo], axis=-1).reshape(*limbs.shape[:-1], 32)
