"""Batch SHA-256 on TPU (lane-parallel over messages).

Reference counterpart: bcos-crypto hash/Sha256.h + the sha256 EVM precompile
(bcos-executor vm/Precompiled.cpp:63). One XLA program hashes the whole batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hash_common import digest_words_to_bytes_be, pad_md64

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_IV = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _schedule(block):
    """Message schedule: block [B, 16] -> W [64, B], unrolled over per-word
    [B] vectors (batch in the VPU minor axis; the scanned [B, 16] window
    version paid a minor-axis concat relayout per step)."""
    words = [block[:, i] for i in range(16)]
    for t in range(48):
        w15 = words[t + 1]
        w2 = words[t + 14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        words.append(words[t] + s0 + words[t + 9] + s1)
    return jnp.stack(words, axis=0)


def _compress(state, block):
    """state [B, 8], block [B, 16] -> new state [B, 8]."""
    w = _schedule(block)  # [64, B]

    def rnd(carry, kw):
        a, b, c, d, e, f, g, h = carry
        k, wt = kw
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    init = tuple(state[:, i] for i in range(8))
    out, _ = lax.scan(rnd, init, (jnp.asarray(_K), w))
    return state + jnp.stack(out, axis=1)


@jax.jit
def sha256_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """blocks [B, M, 16] uint32 BE words, nblocks [B] -> digests [B, 8] uint32."""
    bsz, m_max, _ = blocks.shape
    state0 = jnp.broadcast_to(jnp.asarray(_IV), (bsz, 8))

    def absorb(state, xs):
        blk, idx = xs
        new = _compress(state, blk)
        return jnp.where((idx < nblocks)[:, None], new, state), None

    state, _ = lax.scan(
        absorb,
        state0,
        (jnp.moveaxis(blocks, 1, 0), jnp.arange(m_max, dtype=jnp.int32)),
    )
    return state


def sha256_batch(msgs) -> np.ndarray:
    """Host convenience: list of bytes -> [B, 32] uint8 digests (device batch)."""
    from ..observability.device import device_span

    # the default shape key is the batch bucket — it approximates the
    # compiled program (the message-block dim also shapes it, so compile
    # counts are a lower bound)
    with device_span("sha256", len(msgs)):
        return sha256_batch_async(msgs)()


def sha256_batch_async(msgs):
    """Dispatch the device batch and defer the sync: returns a resolver
    () -> [B, 32] uint8. Lets callers queue several hash programs (tx
    root, receipts root, state root) before paying any device round
    trip."""
    n = len(msgs)
    blocks, nblocks = pad_md64(msgs)  # batch dim bucketed; slice below
    words = sha256_blocks(jnp.asarray(blocks), jnp.asarray(nblocks))
    # analysis: allow(host-sync, deferred resolver — the sync happens when
    # the caller RESOLVES the plane future, not at dispatch)
    return lambda: digest_words_to_bytes_be(np.asarray(words))[:n]


# -- progaudit shape spec (analysis/progaudit: canonical audited bucket) -----
PROGSPEC = {
    "sha256_blocks": {
        "bucket": 256,
        "inputs": lambda b: [((b, 1, 16), "uint32"), ((b,), "int32")],
    },
}
