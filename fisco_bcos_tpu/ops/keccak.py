"""Batch Keccak-256 on TPU.

Replaces the per-message CPU keccak of the reference (bcos-crypto
hash/Keccak256.h via OpenSSL EVP; hot in tx hashing, Transaction.h:64-84
verify, merkle builds) with a lane-parallel formulation: thousands of
independent messages hashed by one XLA program.

64-bit lanes are modeled as (lo, hi) uint32 pairs — TPUs have no 64-bit
integer datapath. The f[1600] permutation runs as a lax.scan over the 24
rounds; multi-block messages scan over block slots with per-lane masking
(static shapes, no data-dependent control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hash_common import digest_words_to_bytes_le, pad_keccak

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_RC_LO = np.array([rc & 0xFFFFFFFF for rc in _RC], dtype=np.uint32)
_RC_HI = np.array([rc >> 32 for rc in _RC], dtype=np.uint32)

# rho rotation offsets r[x][y] and the pi lane permutation, flattened to lane
# index = x + 5y: for each destination lane, (source lane, rotation).
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_PI: list[tuple[int, int]] = [(0, 0)] * 25
for _x in range(5):
    for _y in range(5):
        _dst = _y + 5 * ((2 * _x + 3 * _y) % 5)
        _PI[_dst] = (_x + 5 * _y, _ROT[_x][_y])


def _chi1(i: int) -> int:
    return (i // 5) * 5 + ((i % 5) + 1) % 5


def _chi2(i: int) -> int:
    return (i // 5) * 5 + ((i % 5) + 2) % 5


def _rotl64(lo, hi, n: int):
    """Rotate a (lo, hi) uint32 pair left by static n."""
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n < 32:
        return (
            (lo << n) | (hi >> (32 - n)),
            (hi << n) | (lo >> (32 - n)),
        )
    n -= 32
    return (
        (hi << n) | (lo >> (32 - n)),
        (lo << n) | (hi >> (32 - n)),
    )


def _round(state, rc):
    """One Keccak-f round, LANE-MAJOR: state = (lo, hi), each a 25-tuple of
    [...] batch arrays.

    The batch lives in the MINOR axis (the 128-lane vector axis) exactly
    like the limb-major EC kernels: every theta/rho/pi/chi term is a full
    VPU-width elementwise op on a [B] vector, and all 25-lane indexing is
    static Python (unrolled), so XLA never relayouts a 25-wide minor axis
    — the previous [B, 25] layout wasted ~4/5 of each vector and paid a
    stack+roll relayout per round."""
    lo, hi = state
    rc_lo, rc_hi = rc
    # theta — column parities c[x] = xor over y of lane[x + 5y]
    c_lo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
    c_hi = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
    d = []
    for x in range(5):
        r_lo, r_hi = _rotl64(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
        d.append((c_lo[(x + 4) % 5] ^ r_lo, c_hi[(x + 4) % 5] ^ r_hi))
    lo = [lo[i] ^ d[i % 5][0] for i in range(25)]
    hi = [hi[i] ^ d[i % 5][1] for i in range(25)]
    # rho + pi — static per-lane rotations into permuted positions
    b_lo = [None] * 25
    b_hi = [None] * 25
    for dst, (src, rot) in enumerate(_PI):
        b_lo[dst], b_hi[dst] = _rotl64(lo[src], hi[src], rot)
    # chi — s[x + 5y] = b[x] ^ (~b[x+1] & b[x+2]) within each row y
    lo = [
        b_lo[i] ^ (~b_lo[_chi1(i)] & b_lo[_chi2(i)]) for i in range(25)
    ]
    hi = [
        b_hi[i] ^ (~b_hi[_chi1(i)] & b_hi[_chi2(i)]) for i in range(25)
    ]
    # iota
    lo[0] = lo[0] ^ rc_lo
    hi[0] = hi[0] ^ rc_hi
    return (tuple(lo), tuple(hi))


def keccak_f1600_lanes(lo, hi):
    """Keccak-f[1600] over lane-major state: 25-tuples of [...] batch
    arrays (scan over the 24 rounds)."""

    def body(state, rc):
        return _round(state, rc), None

    (lo, hi), _ = lax.scan(
        body, (tuple(lo), tuple(hi)), (jnp.asarray(_RC_LO), jnp.asarray(_RC_HI))
    )
    return lo, hi




@jax.jit
def keccak256_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Sponge over pre-padded blocks.

    blocks: [B, M, 17, 2] uint32 (rate lanes as lo/hi), nblocks: [B] int32.
    Returns digests as [B, 8] uint32 little-endian words.

    Internally lane-major: the state is 25 independent [B] vectors (batch
    in the VPU's minor axis), so the whole permutation is full-width
    elementwise work with static lane indexing — the one relayout left is
    the final 8-word squeeze."""
    bsz, m_max, lanes, _ = blocks.shape
    zeros = jnp.zeros((bsz,), jnp.uint32)
    lo0 = (zeros,) * 25
    hi0 = (zeros,) * 25

    def absorb(state, xs):
        lo, hi = state
        blk, idx = xs  # blk [17, 2, B]: lane rows are contiguous [B] slices
        alo = tuple(
            lo[l] ^ blk[l, 0] if l < lanes else lo[l] for l in range(25)
        )
        ahi = tuple(
            hi[l] ^ blk[l, 1] if l < lanes else hi[l] for l in range(25)
        )
        plo, phi = keccak_f1600_lanes(alo, ahi)
        active = idx < nblocks
        return (
            tuple(jnp.where(active, plo[l], lo[l]) for l in range(25)),
            tuple(jnp.where(active, phi[l], hi[l]) for l in range(25)),
        ), None

    # one up-front transpose to [M, 17, 2, B] so every absorbed lane is a
    # contiguous batch row inside the scan
    (lo, hi), _ = lax.scan(
        absorb,
        (lo0, hi0),
        (jnp.moveaxis(blocks, 0, -1), jnp.arange(m_max, dtype=jnp.int32)),
    )
    # squeeze 32 bytes = lanes 0..3 -> words [lo0, hi0, lo1, hi1, ...]
    out = jnp.stack(
        [lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]], axis=-1
    )
    return out


def keccak256_batch(msgs) -> np.ndarray:
    """Host convenience: list of bytes -> [B, 32] uint8 digests (device batch)."""
    from ..observability.device import device_span
    from .hash_common import bucket_batch

    n = len(msgs)
    # shape key approximates the compiled program (batch bucket only — the
    # message-block dim also shapes it, so compile counts are a lower bound)
    with device_span("keccak256", n, shape_key=bucket_batch(n)):
        return keccak256_batch_async(msgs)()


def keccak256_batch_async(msgs):
    """Dispatch the device batch and defer the sync: returns a resolver
    () -> [B, 32] uint8. Lets callers queue several hash programs (tx
    root, receipts root, state root) before paying any device round
    trip."""
    n = len(msgs)
    blocks, nblocks = pad_keccak(msgs)  # batch dim bucketed; slice below
    words = keccak256_blocks(jnp.asarray(blocks), jnp.asarray(nblocks))
    # analysis: allow(host-sync, deferred resolver — the sync happens when
    # the caller RESOLVES the plane future, not at dispatch)
    return lambda: digest_words_to_bytes_le(np.asarray(words))[:n]


# -- progaudit shape spec (analysis/progaudit: canonical audited bucket) -----
PROGSPEC = {
    "keccak256_blocks": {
        "bucket": 256,
        "inputs": lambda b: [((b, 1, 17, 2), "uint32"), ((b,), "int32")],
    },
}
