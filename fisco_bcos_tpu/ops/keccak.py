"""Batch Keccak-256 on TPU.

Replaces the per-message CPU keccak of the reference (bcos-crypto
hash/Keccak256.h via OpenSSL EVP; hot in tx hashing, Transaction.h:64-84
verify, merkle builds) with a lane-parallel formulation: thousands of
independent messages hashed by one XLA program.

64-bit lanes are modeled as (lo, hi) uint32 pairs — TPUs have no 64-bit
integer datapath. The f[1600] permutation runs as a lax.scan over the 24
rounds; multi-block messages scan over block slots with per-lane masking
(static shapes, no data-dependent control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hash_common import digest_words_to_bytes_le, pad_keccak

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_RC_LO = np.array([rc & 0xFFFFFFFF for rc in _RC], dtype=np.uint32)
_RC_HI = np.array([rc >> 32 for rc in _RC], dtype=np.uint32)

# rho rotation offsets r[x][y] and the pi lane permutation, flattened to lane
# index = x + 5y: for each destination lane, (source lane, rotation).
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_PI: list[tuple[int, int]] = [(0, 0)] * 25
for _x in range(5):
    for _y in range(5):
        _dst = _y + 5 * ((2 * _x + 3 * _y) % 5)
        _PI[_dst] = (_x + 5 * _y, _ROT[_x][_y])


def _rotl64(lo, hi, n: int):
    """Rotate a (lo, hi) uint32 pair left by static n."""
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n < 32:
        return (
            (lo << n) | (hi >> (32 - n)),
            (hi << n) | (lo >> (32 - n)),
        )
    n -= 32
    return (
        (hi << n) | (lo >> (32 - n)),
        (lo << n) | (hi >> (32 - n)),
    )


def _round(state, rc):
    """One Keccak-f round. state = (lo, hi) each [..., 25]."""
    lo, hi = state
    rc_lo, rc_hi = rc
    shape = lo.shape[:-1]
    # theta — column parities; lane index = x + 5y, so reshape to [..., y, x]
    lo5 = lo.reshape(shape + (5, 5))
    hi5 = hi.reshape(shape + (5, 5))
    c_lo = lo5[..., 0, :] ^ lo5[..., 1, :] ^ lo5[..., 2, :] ^ lo5[..., 3, :] ^ lo5[..., 4, :]
    c_hi = hi5[..., 0, :] ^ hi5[..., 1, :] ^ hi5[..., 2, :] ^ hi5[..., 3, :] ^ hi5[..., 4, :]
    c1_lo, c1_hi = _rotl64(jnp.roll(c_lo, -1, axis=-1), jnp.roll(c_hi, -1, axis=-1), 1)
    d_lo = jnp.roll(c_lo, 1, axis=-1) ^ c1_lo
    d_hi = jnp.roll(c_hi, 1, axis=-1) ^ c1_hi
    lo5 = lo5 ^ d_lo[..., None, :]
    hi5 = hi5 ^ d_hi[..., None, :]
    lo = lo5.reshape(shape + (25,))
    hi = hi5.reshape(shape + (25,))
    # rho + pi — per-lane static rotations into permuted positions
    b_lo = [None] * 25
    b_hi = [None] * 25
    for dst, (src, rot) in enumerate(_PI):
        b_lo[dst], b_hi[dst] = _rotl64(lo[..., src], hi[..., src], rot)
    b_lo = jnp.stack(b_lo, axis=-1).reshape(shape + (5, 5))
    b_hi = jnp.stack(b_hi, axis=-1).reshape(shape + (5, 5))
    # chi
    n1_lo = jnp.roll(b_lo, -1, axis=-1)
    n2_lo = jnp.roll(b_lo, -2, axis=-1)
    n1_hi = jnp.roll(b_hi, -1, axis=-1)
    n2_hi = jnp.roll(b_hi, -2, axis=-1)
    lo = (b_lo ^ (~n1_lo & n2_lo)).reshape(shape + (25,))
    hi = (b_hi ^ (~n1_hi & n2_hi)).reshape(shape + (25,))
    # iota
    lo = lo.at[..., 0].set(lo[..., 0] ^ rc_lo)
    hi = hi.at[..., 0].set(hi[..., 0] ^ rc_hi)
    return (lo, hi)


def keccak_f1600(lo: jax.Array, hi: jax.Array):
    """Keccak-f[1600] over [..., 25] lane pairs (scan over the 24 rounds)."""

    def body(state, rc):
        return _round(state, rc), None

    (lo, hi), _ = lax.scan(body, (lo, hi), (jnp.asarray(_RC_LO), jnp.asarray(_RC_HI)))
    return lo, hi


@jax.jit
def keccak256_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Sponge over pre-padded blocks.

    blocks: [B, M, 17, 2] uint32 (rate lanes as lo/hi), nblocks: [B] int32.
    Returns digests as [B, 8] uint32 little-endian words.
    """
    bsz, m_max, lanes, _ = blocks.shape
    lo0 = jnp.zeros((bsz, 25), jnp.uint32)
    hi0 = jnp.zeros((bsz, 25), jnp.uint32)

    def absorb(state, xs):
        lo, hi = state
        blk, idx = xs  # blk [B, 17, 2]
        alo = lo.at[:, :lanes].set(lo[:, :lanes] ^ blk[..., 0])
        ahi = hi.at[:, :lanes].set(hi[:, :lanes] ^ blk[..., 1])
        plo, phi = keccak_f1600(alo, ahi)
        active = (idx < nblocks)[:, None]
        return (
            jnp.where(active, plo, lo),
            jnp.where(active, phi, hi),
        ), None

    (lo, hi), _ = lax.scan(
        absorb,
        (lo0, hi0),
        (jnp.moveaxis(blocks, 1, 0), jnp.arange(m_max, dtype=jnp.int32)),
    )
    # squeeze 32 bytes = lanes 0..3 -> words [lo0, hi0, lo1, hi1, ...]
    out = jnp.stack([lo[:, 0], hi[:, 0], lo[:, 1], hi[:, 1], lo[:, 2], hi[:, 2], lo[:, 3], hi[:, 3]], axis=-1)
    return out


def keccak256_batch(msgs) -> np.ndarray:
    """Host convenience: list of bytes -> [B, 32] uint8 digests (device batch)."""
    return keccak256_batch_async(msgs)()


def keccak256_batch_async(msgs):
    """Dispatch the device batch and defer the sync: returns a resolver
    () -> [B, 32] uint8. Lets callers queue several hash programs (tx
    root, receipts root, state root) before paying any device round
    trip."""
    blocks, nblocks = pad_keccak(msgs)
    words = keccak256_blocks(jnp.asarray(blocks), jnp.asarray(nblocks))
    return lambda: digest_words_to_bytes_le(np.asarray(words))
