"""Crypto plugin layer — the CryptoSuite seam (reference: bcos-crypto).

``ref/`` holds the pure-Python CPU reference implementations (golden vectors);
``suites`` (added with the batch plane) holds the CryptoSuite implementations
selectable at node boot, mirroring ProtocolInitializer.cpp:51-99's
sm_crypto ? SM3+SM2+SM4 : Keccak256+Secp256k1+AES choice.
"""
