"""BLSCrypto — the BLS12-381 aggregate-signature scheme in the CryptoSuite
plugin layer (the QC subsystem's heavy rung).

Single-item sign/verify ride the pure-Python reference
(:mod:`.ref.bls12_381`) with cached point deserialization — committee
pubkeys and quorum signatures deserialize once per process, not once per
check. Aggregate verification — THE hot call: one pairing check admits a
whole quorum — routes through the shared DevicePlane as the
``bls_aggregate_verify`` op on whatever lane the caller tagged (consensus
for QC admission), merging concurrent certificate checks from block-sync /
lightnode header storms into one jitted pairing program. CPU backends and
sub-threshold batches take the bit-identical host pairing, exactly the
``use_native_batch`` contract the other curves follow.

Key model: BLS keypairs are DERIVED (secret scalar mod r) from the node's
main consensus secret, and the committee's BLS pubkeys are registered in
the consensus-node table (``ConsensusNode.qc_pub``) — registration is the
proof-of-possession boundary that makes same-message aggregation
rogue-key safe (consensus/qc.py docs).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from ..crypto.ref import bls12_381 as ref
from .suite import SignatureCrypto, KeyPair, use_native_batch


@lru_cache(maxsize=4096)
def _g1_point(pub48: bytes):
    """Cached, validated pubkey deserialization (None = malformed/out of
    subgroup). The cache is what makes per-quorum aggregate verification
    pay only the pairing, not 2f+1 subgroup checks."""
    try:
        return ref.decompress_g1(pub48)
    except ValueError:
        return None


@lru_cache(maxsize=4096)
def _g2_point(sig96: bytes):
    try:
        return ref.decompress_g2(sig96)
    except ValueError:
        return None


@lru_cache(maxsize=1024)
def _apk_point(pubs: tuple[bytes, ...]):
    """Aggregate pubkey for a signer set (quorum bitmaps repeat across
    rounds, so the G1 additions amortize too)."""
    acc = None
    for p in pubs:
        pt = _g1_point(p)
        if pt is None:
            return None
        acc = ref.ec_add(acc, pt, ref.FP_OPS)
    return acc


def _aggregate_plane_exec(impl: "BLSCrypto"):
    """Plane executor: merge every queued request's checks into ONE
    pairing batch; one result row per check, sliced back per request."""

    def run(reqs):
        checks: list = []
        for r in reqs:
            checks.extend(r.payload)
        ok = impl._aggregate_verify_merged(checks)
        out, lo = [], 0
        for r in reqs:
            out.append(ok[lo : lo + r.n])
            lo += r.n
        return out

    return run


class BLSCrypto(SignatureCrypto):
    """Min-pubkey-size BLS: 48-byte G1 pubkeys, 96-byte G2 signatures,
    same-message aggregation (the QC case)."""

    name = "bls12_381"
    sig_len = 96

    def generate_keypair(self, secret: int | None = None) -> KeyPair:
        import secrets as _secrets

        if secret is None:
            secret = int.from_bytes(_secrets.token_bytes(32), "big")
        sk, pub = ref.keygen(secret)
        return KeyPair(sk, pub)

    def sign(self, kp: KeyPair, msg_hash: bytes) -> bytes:
        return ref.sign(kp.secret, msg_hash)

    def verify(self, pub: bytes, msg_hash: bytes, sig: bytes) -> bool:
        pk = _g1_point(bytes(pub))
        s = _g2_point(bytes(sig))
        if pk is None or s is None:
            return False
        return ref.pairing_check(
            [(ref.ec_neg(ref.G1, ref.FP_OPS), s), (pk, ref.hash_to_g2(bytes(msg_hash)))]
        )

    def recover(self, msg_hash: bytes, sig: bytes) -> bytes:
        raise ValueError("BLS signatures carry no recoverable public key")

    def batch_verify(self, msg_hashes, pubs, sigs) -> np.ndarray:
        """Independent-message batch (per-signer isolation fallback): host
        loop over cached points — distinct messages have no shared pairing
        structure worth a merged program at QC sizes."""
        return np.array(
            [
                self.verify(bytes(p), bytes(h), bytes(s))
                for h, p, s in zip(msg_hashes, pubs, sigs)
            ],
            dtype=bool,
        )

    def batch_recover(self, msg_hashes, sigs):
        raise ValueError("BLS signatures carry no recoverable public key")

    # -- aggregation (the QC surface) ---------------------------------------

    def aggregate(self, sigs: list[bytes]) -> bytes:
        """Sum the G2 signatures into one 96-byte certificate signature."""
        acc = None
        for s in sigs:
            pt = _g2_point(bytes(s))
            if pt is None:
                raise ValueError("malformed signature in aggregate")
            acc = ref.ec_add(acc, pt, ref.FP2_OPS)
        return ref.compress_g2(acc)

    def aggregate_verify(
        self, pubs: list[bytes], msg_hash: bytes, agg_sig: bytes
    ) -> bool:
        """One pairing check for the whole signer set (same message)."""
        return bool(
            self.aggregate_verify_batch([(tuple(pubs), msg_hash, agg_sig)])[0]
        )

    def aggregate_verify_batch(self, checks) -> np.ndarray:
        """checks: [(pubs tuple, msg_hash, agg_sig)] -> bool[B], routed
        through the DevicePlane (op ``bls_aggregate_verify``) so
        concurrent QC admissions merge into one pairing program."""
        checks = [
            (tuple(bytes(p) for p in pubs), bytes(m), bytes(s))
            for pubs, m, s in checks
        ]
        from ..device.plane import get_plane, plane_route, plane_wait

        if plane_route() and checks:
            return plane_wait(
                get_plane().submit(
                    "bls_aggregate_verify",
                    checks,
                    len(checks),
                    _aggregate_plane_exec(self),
                )
            )
        return self._aggregate_verify_merged(checks)

    def _aggregate_verify_merged(self, checks) -> np.ndarray:
        """The merged-batch body both dispatch modes share. Deserialization
        and hash-to-G2 are host-side (cached); the pairing runs on device
        for large merged batches on accelerator backends, else on the
        bit-identical host reference."""
        from ..observability.device import device_span
        from ..ops.hash_common import bucket_batch

        triples = []
        for pubs, msg, agg in checks:
            apk = _apk_point(pubs) if pubs else None
            sig = _g2_point(agg)
            hm = ref.hash_to_g2(msg) if apk is not None and sig is not None else None
            triples.append((apk, sig, hm))
        n = len(triples)
        from ..ops import bls12_381 as bls_ops

        if use_native_batch(n):
            from .suite import _note_dispatch_path

            _note_dispatch_path("bls_aggregate_verify", "native")
            return bls_ops.host_pairing_check_batch(triples)
        from .suite import _note_dispatch_path

        _note_dispatch_path("bls_aggregate_verify", "device")
        with device_span(
            "bls_aggregate_verify", n, shape_key=bucket_batch(max(n, 1))
        ):
            return bls_ops.pairing_check_batch(triples)


    # -- succinct header sync (the multi-pairing surface) -------------------

    def multi_pairing_verify(self, checks) -> bool:
        """ONE accept/reject for a whole set of aggregate checks.

        ``checks`` is the same ``[(pubs tuple, msg_hash, agg_sig)]`` shape as
        :meth:`aggregate_verify_batch`, but instead of K independent pairing
        checks the set folds into a single K+1-pair product via a
        Fiat-Shamir random linear combination: scalars ``r_k`` are drawn
        from a hash transcript over every ``(msg, sig)`` AFTER all of them
        are fixed, and

            e(-G1, sum_k r_k*sig_k) * prod_k e(r_k*apk_k, Hm_k) == 1

        holds for random r_k iff every per-check equation holds (soundness
        error ~2^-128 — an adversary would have to predict the transcript).
        The succinct header-sync payoff: K header QCs cost ONE shared
        squaring chain in the Miller stage and ONE final exponentiation
        instead of K full pairing checks. Callers that need to know WHICH
        check failed fall back to :meth:`aggregate_verify_batch`.
        """
        import hashlib

        checks = [
            (tuple(bytes(p) for p in pubs), bytes(m), bytes(s))
            for pubs, m, s in checks
        ]
        if not checks:
            return True
        triples = []
        for pubs, msg, agg in checks:
            apk = _apk_point(pubs) if pubs else None
            sig = _g2_point(agg)
            if apk is None or sig is None:
                return False
            triples.append((apk, sig, ref.hash_to_g2(msg)))
        # transcript binds every message and signature before any scalar
        # is drawn — the Fiat-Shamir ordering that makes the RLC sound
        tr = hashlib.sha256()
        for (_, msg, agg) in checks:
            tr.update(len(msg).to_bytes(4, "big"))
            tr.update(msg)
            tr.update(agg)
        seed = tr.digest()
        scalars = [
            max(
                1,
                int.from_bytes(
                    hashlib.sha256(seed + k.to_bytes(8, "big")).digest()[:16],
                    "big",
                ),
            )
            for k in range(len(triples))
        ]
        sig_acc = None
        pairs = []
        for r, (apk, sig, hm) in zip(scalars, triples):
            sig_acc = ref.ec_add(
                sig_acc, ref.ec_mul(sig, r, ref.FP2_OPS), ref.FP2_OPS
            )
            pairs.append((ref.ec_mul(apk, r, ref.FP_OPS), hm))
        pairs.insert(0, (ref.ec_neg(ref.G1, ref.FP_OPS), sig_acc))

        from ..observability.device import device_span
        from ..ops import bls12_381 as bls_ops
        from .suite import _note_dispatch_path

        n = len(pairs)
        if use_native_batch(n):
            _note_dispatch_path("bls_multi_pairing", "native")
            return bool(bls_ops.host_multi_pairing_check(pairs))
        _note_dispatch_path("bls_multi_pairing", "device")
        with device_span(
            "bls_multi_pairing", n, shape_key=bls_ops.multi_pairing_pad(n)
        ):
            return bool(bls_ops.multi_pairing_check(pairs))


def bls_suite():
    """Keccak256 + BLS12-381 — the aggregate-QC suite, registered beside
    ecdsa_suite/sm_suite (reference: the ProtocolInitializer suite choice)."""
    from .suite import CryptoSuite, Keccak256

    return CryptoSuite(Keccak256(), BLSCrypto())
