"""CryptoSuite — the crypto plugin seam, with first-class batch APIs.

Mirrors the capability surface of the reference's plugin layer
(bcos-crypto/interfaces/crypto/CryptoSuite.h:33-69, Signature.h:31-58,
Hash.h:37-60; suite selection in libinitializer/ProtocolInitializer.cpp:51-99:
``sm_crypto ? (SM3+SM2+SM4) : (Keccak256+Secp256k1+AES)``) — but where the
reference's `SignatureCrypto` is single-item only (the TPU batch API is the
whole point of this build, per BASELINE.json), every hash and signature
implementation here carries `hash_batch` / `batch_verify` / `batch_recover`
that run one fused device program over the whole batch.

Single-item calls use the pure-CPU reference implementations (crypto/ref) —
device round-trips don't pay off below ~hundreds of items; batch calls go to
the ops kernels. Both produce bit-identical results (golden-vector tested) —
any divergence would fork a chain.
"""

from __future__ import annotations

import os
import secrets
import threading
from dataclasses import dataclass

import numpy as np

from ..ops import keccak as keccak_ops
from ..ops import merkle as merkle_ops
from ..ops import secp256k1 as secp_ops
from ..ops import sha256 as sha256_ops
from ..ops import sm2 as sm2_ops
from ..ops import sm3 as sm3_ops
from ..utils.bytesutil import right160
from .ref import ecdsa as ref_ecdsa
from .ref import ed25519 as ref_ed25519
from .ref.keccak import keccak256 as ref_keccak256
from .ref.sha2 import sha256 as ref_sha256
from .ref.sm3 import sm3 as ref_sm3

# ---------------------------------------------------------------------------
# Hash implementations
# ---------------------------------------------------------------------------


def _hash_plane_exec(name: str, batch_async_direct):
    """Plane executor for one hash op: merge every queued request's messages
    into ONE bucket-padded device program, dispatch it WITHOUT syncing, and
    hand each request a slice resolver — queued hash programs from several
    callers overlap on device before anyone pays the first round trip."""

    def run(reqs):
        msgs: list[bytes] = []
        spans = []
        for r in reqs:
            spans.append((len(msgs), len(msgs) + r.n))
            msgs.extend(r.payload)
        from ..observability.device import device_span
        from ..ops.hash_common import bucket_batch

        # span covers the dispatch only (the sync happens in the caller's
        # resolver); the compile counter keys on the batch bucket as usual
        with device_span(name, len(msgs), shape_key=bucket_batch(max(len(msgs), 1))):
            resolve = batch_async_direct(msgs)
        memo: list = []
        lock = threading.Lock()

        def realize():
            with lock:
                if not memo:
                    memo.append(resolve())
                return memo[0]

        return [lambda lo=lo, hi=hi: realize()[lo:hi] for lo, hi in spans]

    return run


class HashImpl:
    """Hash interface (reference: bcos-crypto Hash.h:37-60 + AnyHasher).

    Batch calls route through the shared :class:`~..device.plane.DevicePlane`
    (coalesced, bucket-padded, priority-laned); ``FISCO_DEVICE_PLANE=0``
    restores the direct per-caller dispatch. Subclasses implement the
    ``_batch_direct`` / ``_batch_async_direct`` pair; the plane executor and
    the passthrough path both go through those, so the two modes cannot
    diverge.
    """

    name: str = ""

    def hash(self, data: bytes) -> bytes:
        raise NotImplementedError

    def _batch_direct(self, msgs) -> np.ndarray:
        """Direct (non-plane) batch dispatch: one device program."""
        raise NotImplementedError

    def _batch_async_direct(self, msgs):
        """Direct deferred-sync dispatch: () -> [B, 32]. Default dispatches
        eagerly; device-backed impls override with their ops *_batch_async
        so the plane executor can defer the sync."""
        out = self._batch_direct(msgs)
        return lambda: out

    def hash_batch(self, msgs) -> np.ndarray:
        """list[bytes] -> [B, 32] uint8 digests, one device program."""
        msgs = list(msgs)
        from ..device.plane import plane_route

        if plane_route() and msgs:
            return self.hash_batch_async(msgs)()
        return self._batch_direct(msgs)

    def hash_batch_async(self, msgs):
        """Dispatch the device batch, defer the sync: () -> [B, 32] uint8.

        Routed through the device plane so concurrent callers' hash
        programs coalesce AND overlap before the first sync (pre-plane,
        this default ran eagerly — each caller synced before the next
        could even dispatch)."""
        msgs = list(msgs)
        from ..device.plane import get_plane, plane_route, plane_wait_deferred

        if plane_route() and msgs:
            fut = get_plane().submit(
                f"hash.{self.name or type(self).__name__}",
                msgs,
                len(msgs),
                _hash_plane_exec(
                    self.name or type(self).__name__, self._batch_async_direct
                ),
            )
            return lambda: plane_wait_deferred(fut)
        return self._batch_async_direct(msgs)


class Keccak256(HashImpl):
    """Single-item host path: native C core when available (native_bind —
    the wedpr/EVP analog), pure-Python reference otherwise; batch path: TPU."""

    name = "keccak256"

    def hash(self, data: bytes) -> bytes:
        from .. import native_bind

        return native_bind.keccak256(data) or ref_keccak256(data)

    def _batch_direct(self, msgs) -> np.ndarray:
        return keccak_ops.keccak256_batch(msgs)

    def _batch_async_direct(self, msgs):
        return keccak_ops.keccak256_batch_async(msgs)


class SM3(HashImpl):
    name = "sm3"

    def hash(self, data: bytes) -> bytes:
        from .. import native_bind

        return native_bind.sm3(data) or ref_sm3(data)

    def _batch_direct(self, msgs) -> np.ndarray:
        return sm3_ops.sm3_batch(msgs)

    def _batch_async_direct(self, msgs):
        return sm3_ops.sm3_batch_async(msgs)


class Sha256(HashImpl):
    name = "sha256"

    def hash(self, data: bytes) -> bytes:
        from .. import native_bind

        return native_bind.sha256(data) or ref_sha256(data)

    def _batch_direct(self, msgs) -> np.ndarray:
        return sha256_ops.sha256_batch(msgs)

    def _batch_async_direct(self, msgs):
        return sha256_ops.sha256_batch_async(msgs)


class Poseidon(HashImpl):
    """SNARK-friendly hash lane (ISSUE 18): the succinct state plane's
    selectable commitment hasher. Single-item path is the pure-Python
    reference (no native core exists); batch path is the jitted sponge.
    Imports are lazy — deriving the Grain/Cauchy constant tables costs
    ~0.2 s and only nodes running `FISCO_STATE_HASH=poseidon` pay it."""

    name = "poseidon"

    def hash(self, data: bytes) -> bytes:
        from .ref.poseidon import poseidon_hash

        return poseidon_hash(data)

    def _batch_direct(self, msgs) -> np.ndarray:
        from ..ops import poseidon as poseidon_ops

        return poseidon_ops.poseidon_batch(msgs)

    def _batch_async_direct(self, msgs):
        from ..ops import poseidon as poseidon_ops

        return poseidon_ops.poseidon_batch_async(msgs)


_HASH_IMPLS: dict[str, type[HashImpl]] = {
    "keccak256": Keccak256,
    "sm3": SM3,
    "sha256": Sha256,
    "poseidon": Poseidon,
}


def hash_impl_by_name(name: str) -> HashImpl:
    """Hash impl registry lookup (`FISCO_STATE_HASH` selection seam). An
    unknown name raises — one node silently falling back to a different
    hasher than its peers would fork the state commitment."""
    try:
        return _HASH_IMPLS[name]()
    except KeyError:
        raise KeyError(f"unknown hash impl: {name!r}") from None


# ---------------------------------------------------------------------------
# Key pairs
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class KeyPair:
    """Secret scalar + uncompressed public key (reference: KeyPairInterface)."""

    secret: int
    pub: bytes  # 64 bytes, x‖y big-endian

    @property
    def pub_x(self) -> int:
        return int.from_bytes(self.pub[:32], "big")

    @property
    def pub_y(self) -> int:
        return int.from_bytes(self.pub[32:], "big")


def _make_keypair(curve: ref_ecdsa.Curve, secret: int | None) -> KeyPair:
    if secret is None:
        secret = secrets.randbelow(curve.n - 1) + 1
    x, y = ref_ecdsa.privkey_to_pubkey(curve, secret)
    return KeyPair(secret, x.to_bytes(32, "big") + y.to_bytes(32, "big"))


# ---------------------------------------------------------------------------
# Signature implementations
# ---------------------------------------------------------------------------

# Batches below this ride the native host loop instead of the device: a
# tunneled device program pays a full round trip (~100ms+) regardless of
# batch size, while the native single-item path is ~0.3ms/sig — the
# break-even sits near a few hundred items.  PBFT QC signature lists
# (3-4 sigs per block, BlockValidator.cpp:141-177) and small-block
# admission are the beneficiaries.  Results are bit-identical across both
# legs (tests/test_native_ec.py pins it).
_SMALL_BATCH = 256


def device_min_batch() -> int:
    """Host-vs-device cutover: batches below this ride the native host loop.

    ``FISCO_DEVICE_MIN_BATCH`` overrides the hardcoded default — the right
    cutover depends on the device round-trip, and a 100ms-RTT tunneled TPU
    breaks even hundreds of items later than a local accelerator. Read per
    call (an env read, ~100ns against a batch dispatch) so operators and
    tests can retune without a restart."""
    raw = os.environ.get("FISCO_DEVICE_MIN_BATCH")
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    return _SMALL_BATCH


def _note_dispatch_path(op: str, path: str) -> None:
    """Labeled counter of which leg a batch actually took (native host loop
    vs device program) — the observable form of the `use_native_batch`
    policy, so a mistuned FISCO_DEVICE_MIN_BATCH shows up in /metrics
    instead of as a silent latency cliff."""
    from ..utils.metrics import REGISTRY

    REGISTRY.counter_add(
        f'fisco_device_dispatch_path_total{{op="{op}",path="{path}"}}',
        1.0,
        help="batch dispatches split by chosen leg (native host vs device)",
    )


_BACKEND_IS_CPU: bool | None = None


def device_backend_is_cpu() -> bool:
    """True when the jax device plane is CPU XLA (no accelerator): there the
    native C loop beats the XLA program at EVERY batch size (~0.3ms/sig vs
    4-16ms/sig of emulated 256-bit limb arithmetic), so batch dispatchers
    should prefer the host path regardless of _SMALL_BATCH. Cached: backend
    identity cannot change within a process."""
    global _BACKEND_IS_CPU
    # analysis: allow(atomicity, idempotent memo — racing initializers both
    # compute the same immutable backend identity, last write wins harmlessly)
    if _BACKEND_IS_CPU is None:
        try:
            import jax

            _BACKEND_IS_CPU = jax.default_backend() == "cpu"
        except Exception:
            _BACKEND_IS_CPU = True
    return _BACKEND_IS_CPU


def use_native_batch(n: int) -> bool:
    """Whether an n-item signature batch should ride the native host loop
    instead of a device program (threshold: :func:`device_min_batch`)."""
    return 0 < n and (n < device_min_batch() or device_backend_is_cpu())


# -- device-path circuit breaker (resilience/) -------------------------------

_DEVICE_BREAKER = None
_DEVICE_BREAKER_LOCK = threading.Lock()


def _device_breaker():
    """Breaker over the compiled device batch plane. It can fail in the
    field — accelerator tunnel loss, device OOM on an oversized trace, a
    driver hiccup — and consensus must keep verifying: each failure falls
    back to the host loop for THAT batch, and repeated failures trip the
    breaker so admission stops paying a doomed device dispatch before every
    fallback. /health reports `device-crypto` degraded while tripped; a
    half-open probe re-closes it when the device plane answers again."""
    global _DEVICE_BREAKER
    if _DEVICE_BREAKER is None:
        from ..resilience import CircuitBreaker

        # double-checked: two racing callers must end up sharing ONE breaker
        # — split breakers would each see half the failures and never trip
        with _DEVICE_BREAKER_LOCK:
            if _DEVICE_BREAKER is None:
                _DEVICE_BREAKER = CircuitBreaker(
                    "device-crypto", failure_threshold=2, reset_timeout=60.0,
                    critical=False,  # host loop keeps serving: slower, not down
                )
    return _DEVICE_BREAKER


def _device_or_host(device_fn, host_fn, *args):
    """Run the compiled device path under the breaker, degrading to the
    bit-identical host loop. The failure only counts against the breaker
    when the host retry of the SAME args succeeds — a data error (bad
    shape/dtype) re-raises from the host path without tripping anything,
    so one malformed batch cannot demote a healthy device plane."""
    breaker = _device_breaker()
    if not breaker.allow():
        return host_fn(*args)
    try:
        out = device_fn(*args)
    except Exception as e:
        try:
            out = host_fn(*args)
        except BaseException:
            # both paths failed: a data error, not a device verdict — free
            # the half-open probe slot or the breaker wedges
            breaker.release_probe()
            raise
        breaker.record_failure(f"{type(e).__name__}: {str(e)[:200]}")
        return out
    breaker.record_success()
    return out


# -- device-plane executors ---------------------------------------------------
#
# One executor per (op, merge-convention): each merges every queued request
# into one batch, runs the impl's merged-batch body (the SAME body the
# passthrough path uses — the two modes cannot diverge), and slices the
# result back per request. Executors run on the plane worker with routing
# disabled, so nested seam calls (ed25519 recover → verify) take the direct
# path instead of deadlocking the worker.


def _verify_plane_exec(impl):
    """(hashes [n,32], pubs [n,64], sigs [n,L]) ndarray triples -> ok[n]."""

    def run(reqs):
        hs = np.concatenate([r.payload[0] for r in reqs], axis=0)
        ps = np.concatenate([r.payload[1] for r in reqs], axis=0)
        sg = np.concatenate([r.payload[2] for r in reqs], axis=0)
        ok = np.asarray(impl._verify_merged(hs, ps, sg))
        out, lo = [], 0
        for r in reqs:
            out.append(ok[lo : lo + r.n])
            lo += r.n
        return out

    return run


def _verify_plane_exec_lists(impl):
    """Same as :func:`_verify_plane_exec` for list-of-bytes payloads
    (ed25519's variable-form signatures)."""

    def run(reqs):
        hs: list[bytes] = []
        ps: list[bytes] = []
        sg: list[bytes] = []
        for r in reqs:
            h, p, s = r.payload
            hs += h
            ps += p
            sg += s
        ok = np.asarray(impl._verify_merged(hs, ps, sg))
        out, lo = [], 0
        for r in reqs:
            out.append(ok[lo : lo + r.n])
            lo += r.n
        return out

    return run


def _recover_plane_exec(impl):
    """(hashes [n,32], sigs [n,L]) -> (pubs [n,64], ok[n]) per request."""

    def run(reqs):
        hs = np.concatenate([r.payload[0] for r in reqs], axis=0)
        sg = np.concatenate([r.payload[1] for r in reqs], axis=0)
        pubs, ok = impl._recover_merged(hs, sg)
        pubs, ok = np.asarray(pubs), np.asarray(ok)
        out, lo = [], 0
        for r in reqs:
            out.append((pubs[lo : lo + r.n], ok[lo : lo + r.n]))
            lo += r.n
        return out

    return run


class SignatureCrypto:
    """Signature interface (reference: Signature.h:31-58) + batch extension.

    sign/verify/recover operate on 32-byte message hashes. `recover` returns
    the 64-byte uncompressed public key or raises; batch variants return
    validity masks instead of raising (invalid lanes lower a bit).
    """

    name: str = ""
    sig_len: int = 0

    def generate_keypair(self, secret: int | None = None) -> KeyPair:
        raise NotImplementedError

    def sign(self, kp: KeyPair, msg_hash: bytes) -> bytes:
        raise NotImplementedError

    def verify(self, pub: bytes, msg_hash: bytes, sig: bytes) -> bool:
        raise NotImplementedError

    def recover(self, msg_hash: bytes, sig: bytes) -> bytes:
        raise NotImplementedError

    def batch_verify(
        self, msg_hashes: np.ndarray, pubs: np.ndarray, sigs: np.ndarray
    ) -> np.ndarray:
        raise NotImplementedError

    def batch_recover(
        self, msg_hashes: np.ndarray, sigs: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError


class Ed25519Crypto(SignatureCrypto):
    """Ed25519 (reference: signature/ed25519/Ed25519Crypto.cpp via wedpr).

    Host-side suite: 96-byte signatures R‖S‖pubkey32 — like the reference's
    SM2 scheme, "recover" parses the appended key then verifies
    (SM2Crypto.cpp:81-91 pattern); ed25519 has no algebraic recovery. The
    secret scalar is the 32-byte seed (little-endian int). Batch calls loop
    on the host: the device batch plane covers the two tx-signing curves
    (secp256k1/SM2); this suite exists for signature-surface parity.
    """

    name = "ed25519"
    sig_len = 96

    def generate_keypair(self, secret: int | None = None) -> KeyPair:
        from .. import native_bind

        if secret is None:
            secret = int.from_bytes(secrets.token_bytes(32), "little")
        seed = (secret % (1 << 256)).to_bytes(32, "little")
        pub = native_bind.ed25519_pubkey(seed) or ref_ed25519.seed_to_pubkey(seed)
        return KeyPair(int.from_bytes(seed, "little"), pub)

    @staticmethod
    def _seed(kp: KeyPair) -> bytes:
        return (kp.secret % (1 << 256)).to_bytes(32, "little")

    def sign(self, kp: KeyPair, msg_hash: bytes) -> bytes:
        from .. import native_bind

        sig = native_bind.ed25519_sign(self._seed(kp), msg_hash)
        if sig is None:
            sig = ref_ed25519.sign(self._seed(kp), msg_hash)
        return sig + kp.pub

    def verify(self, pub: bytes, msg_hash: bytes, sig: bytes) -> bool:
        from .. import native_bind

        ok = native_bind.ed25519_verify(pub[:32], msg_hash, sig[:64])
        if ok is not None:
            return ok
        return ref_ed25519.verify(pub[:32], msg_hash, sig[:64])

    def recover(self, msg_hash: bytes, sig: bytes) -> bytes:
        pub = sig[64:96]
        if not self.verify(pub, msg_hash, sig[:64] + pub):
            raise ValueError("ed25519 signature does not verify")
        return pub

    def batch_verify(self, msg_hashes, pubs, sigs) -> np.ndarray:
        """One fused device program for the whole batch: all curve math
        (decompression, dual ladder, cofactored identity check) on device;
        SHA-512 challenges on host (ops/ed25519.py module docstring).
        Small batches and CPU-only backends ride the native host loop like
        the other curves (use_native_batch) — a QC list of 4 signatures
        must never pay a tunnel round trip or emulated-XLA limb math.
        Routed through the device plane (merged with concurrent callers;
        the host-vs-device cutover applies to the MERGED size)."""
        hashes = [bytes(h) for h in msg_hashes]
        pub_list = [bytes(p) for p in pubs]
        sig_list = [bytes(s) for s in sigs]
        from ..device.plane import get_plane, plane_route, plane_wait

        if plane_route() and sig_list:
            return plane_wait(get_plane().submit(
                "verify.ed25519",
                (hashes, pub_list, sig_list),
                len(sig_list),
                _verify_plane_exec_lists(self),
            ))
        return self._verify_merged(hashes, pub_list, sig_list)

    def _verify_merged(self, hashes, pub_list, sig_list) -> np.ndarray:
        if use_native_batch(len(sig_list)):
            from .. import native_bind

            if native_bind.load() is not None:
                _note_dispatch_path("ed25519_verify", "native")
                return np.array(
                    [
                        native_bind.ed25519_verify(p[:32], h, s[:64])
                        for h, p, s in zip(hashes, pub_list, sig_list)
                    ],
                    dtype=bool,
                )
        from ..ops import ed25519 as ed_ops

        _note_dispatch_path("ed25519_verify", "device")
        return ed_ops.verify_batch(hashes, pub_list, sig_list)

    def batch_recover(self, msg_hashes, sigs):
        """Parse the appended key, then device-batch-verify (ed25519 has no
        algebraic recovery; the 96-byte R‖S‖pub format carries the key).

        Malformed (short) signatures lower their lane's ok bit — they must
        never crash, and never reach the device as zero-filled dummies (a
        zero pubkey decompresses to a torsion point that can verify)."""
        sigs = [bytes(s) for s in sigs]
        wellformed = np.array([len(s) >= 96 for s in sigs])
        safe = [
            s if good else b"\x00" * 32 + b"\x01" + b"\x00" * 63
            for s, good in zip(sigs, wellformed)
        ]
        pubs = [s[64:96] for s in safe]
        ok = self.batch_verify(msg_hashes, pubs, safe) & wellformed
        out = np.frombuffer(
            b"".join(
                p if good else b"\x00" * 32 for p, good in zip(pubs, ok)
            ),
            np.uint8,
        ).reshape(-1, 32)
        return out, np.asarray(ok)


class Secp256k1Crypto(SignatureCrypto):
    """65-byte r‖s‖v signatures, v ∈ {0..3} ∪ {27, 28}
    (reference: Secp256k1Crypto.cpp:32-136).

    Single-item paths go through the native C core when available (the
    wedpr-FFI analog — every PBFT packet and single-tx RPC admission pays
    this latency, Secp256k1Crypto.cpp:57/:85), falling back to the
    bit-identical pure-Python reference."""

    name = "secp256k1"
    sig_len = 65

    def generate_keypair(self, secret: int | None = None) -> KeyPair:
        if secret is None:
            return _make_keypair(ref_ecdsa.SECP256K1, None)
        from .. import native_bind

        pub = native_bind.ec_pubkey("secp256k1", secret)
        if pub is None:
            return _make_keypair(ref_ecdsa.SECP256K1, secret)
        return KeyPair(secret, pub)

    def sign(self, kp: KeyPair, msg_hash: bytes) -> bytes:
        from .. import native_bind

        out = native_bind.secp256k1_sign(msg_hash, kp.secret)
        if out is None:
            out = ref_ecdsa.ecdsa_sign(msg_hash, kp.secret)
        r, s, v = out
        return r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([v])

    def verify(self, pub: bytes, msg_hash: bytes, sig: bytes) -> bool:
        from .. import native_bind

        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        ok = native_bind.secp256k1_verify(msg_hash, r, s, pub)
        if ok is not None:
            return ok
        p = (int.from_bytes(pub[:32], "big"), int.from_bytes(pub[32:], "big"))
        return ref_ecdsa.ecdsa_verify(msg_hash, r, s, p)

    def recover(self, msg_hash: bytes, sig: bytes) -> bytes:
        from .. import native_bind

        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        native = native_bind.secp256k1_recover(msg_hash, r, s, sig[64])
        if native is not None:
            if not native:
                raise ValueError("secp256k1 recover failed")
            return native
        pub = ref_ecdsa.ecdsa_recover(msg_hash, r, s, sig[64])
        if pub is None:
            raise ValueError("secp256k1 recover failed")
        x, y = pub
        return x.to_bytes(32, "big") + y.to_bytes(32, "big")

    def batch_verify(self, msg_hashes, pubs, sigs) -> np.ndarray:
        sigs = np.asarray(sigs, dtype=np.uint8)
        hashes = np.asarray(msg_hashes, dtype=np.uint8)
        pubs = np.asarray(pubs, dtype=np.uint8)
        from ..device.plane import get_plane, plane_route, plane_wait

        if plane_route() and len(sigs):
            return plane_wait(get_plane().submit(
                "verify.secp256k1",
                (hashes, pubs, sigs),
                len(sigs),
                _verify_plane_exec(self),
            ))
        return self._verify_merged(hashes, pubs, sigs)

    def _verify_merged(self, hashes, pubs, sigs) -> np.ndarray:
        n = len(sigs)
        if use_native_batch(n):
            from .. import native_bind

            out = native_bind.secp256k1_verify_batch(
                np.ascontiguousarray(hashes).tobytes(),
                np.ascontiguousarray(sigs[:, :32]).tobytes(),
                np.ascontiguousarray(sigs[:, 32:64]).tobytes(),
                np.ascontiguousarray(pubs).tobytes(),
                n,
            )
            if out is not None:
                _note_dispatch_path("secp256k1_verify", "native")
                return np.asarray(out, dtype=bool)
        _note_dispatch_path("secp256k1_verify", "device")
        return _device_or_host(
            secp_ops.verify_batch, self._host_verify_loop,
            hashes, sigs[:, :32], sigs[:, 32:64], pubs,
        )

    def _host_verify_loop(self, hashes, rs, ss, pubs) -> np.ndarray:
        """Degraded-mode fallback: per-item verify on the host (native C or
        pure-Python ref) — slow but bit-identical in outcome."""
        return np.array(
            [
                self.verify(
                    bytes(pubs[i]),
                    bytes(hashes[i]),
                    bytes(rs[i]) + bytes(ss[i]) + b"\x00",
                )
                for i in range(len(hashes))
            ],
            dtype=bool,
        )

    def _host_recover_loop(self, hashes, sigs):
        n = len(sigs)
        pubs = np.zeros((n, 64), dtype=np.uint8)
        ok = np.zeros(n, dtype=bool)
        for i in range(n):
            try:
                pub = self.recover(bytes(hashes[i]), bytes(sigs[i]))
            except ValueError:
                continue
            pubs[i] = np.frombuffer(pub, dtype=np.uint8)
            ok[i] = True
        return pubs, ok

    def batch_recover(self, msg_hashes, sigs):
        sigs = np.asarray(sigs, dtype=np.uint8)
        hashes = np.asarray(msg_hashes, dtype=np.uint8)
        from ..device.plane import get_plane, plane_route, plane_wait

        if plane_route() and len(sigs):
            return plane_wait(get_plane().submit(
                "recover.secp256k1",
                (hashes, sigs),
                len(sigs),
                _recover_plane_exec(self),
            ))
        return self._recover_merged(hashes, sigs)

    def _recover_merged(self, hashes, sigs):
        n = len(sigs)
        if use_native_batch(n):
            from .. import native_bind

            out = native_bind.secp256k1_recover_batch(
                np.ascontiguousarray(hashes).tobytes(),
                np.ascontiguousarray(sigs[:, :32]).tobytes(),
                np.ascontiguousarray(sigs[:, 32:64]).tobytes(),
                np.ascontiguousarray(sigs[:, 64]).tobytes(),
                n,
            )
            if out is not None:
                _note_dispatch_path("secp256k1_recover", "native")
                pubs_raw, oks = out
                pubs = np.frombuffer(pubs_raw, np.uint8).reshape(n, 64).copy()
                ok = np.asarray(oks, dtype=bool)
                pubs[~ok] = 0
                return pubs, ok
        _note_dispatch_path("secp256k1_recover", "device")
        return _device_or_host(
            secp_ops.recover_batch, self._host_recover_loop, hashes, sigs
        )


class SM2Crypto(SignatureCrypto):
    """128-byte r‖s‖pubkey signatures; "recover" parses the carried pubkey and
    verifies (reference: SM2Crypto.cpp:29-91 — sign appends the pubkey,
    recover = parse-pub-then-verify)."""

    name = "sm2"
    sig_len = 128

    @staticmethod
    def _e_bytes(pub: bytes, msg_hash: bytes) -> bytes:
        """e = SM3(ZA ‖ M) with the default user id, riding the native
        hasher when available (layout lives in one place: ecdsa.sm2_za_bytes)."""
        from .. import native_bind

        return ref_ecdsa.sm2_e_bytes(
            pub, msg_hash, sm3_fn=lambda b: native_bind.sm3(b) or ref_sm3(b)
        )

    def generate_keypair(self, secret: int | None = None) -> KeyPair:
        if secret is None:
            return _make_keypair(ref_ecdsa.SM2_CURVE, None)
        from .. import native_bind

        pub = native_bind.ec_pubkey("sm2", secret)
        if pub is None:
            return _make_keypair(ref_ecdsa.SM2_CURVE, secret)
        return KeyPair(secret, pub)

    def sign(self, kp: KeyPair, msg_hash: bytes) -> bytes:
        from .. import native_bind

        out = None
        if native_bind.load() is not None:
            out = native_bind.sm2_sign(self._e_bytes(kp.pub, msg_hash), kp.secret)
        if out is None:
            out = ref_ecdsa.sm2_sign(msg_hash, kp.secret)
        r, s = out
        return r.to_bytes(32, "big") + s.to_bytes(32, "big") + kp.pub

    def verify(self, pub: bytes, msg_hash: bytes, sig: bytes) -> bool:
        from .. import native_bind

        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        if native_bind.load() is not None:
            ok = native_bind.sm2_verify(self._e_bytes(pub, msg_hash), r, s, pub)
            if ok is not None:
                return ok
        p = (int.from_bytes(pub[:32], "big"), int.from_bytes(pub[32:], "big"))
        return ref_ecdsa.sm2_verify(msg_hash, r, s, p)

    def recover(self, msg_hash: bytes, sig: bytes) -> bytes:
        pub = sig[64:128]
        if not self.verify(pub, msg_hash, sig[:64] + pub):
            raise ValueError("sm2 recover: carried pubkey fails verification")
        return pub

    def _native_batch_verify(self, hashes, pubs, rs, ss):
        """Native host loop for sub-threshold batches (e computed with the
        native SM3); None when the native core is unavailable."""
        from .. import native_bind

        if native_bind.load() is None:
            return None
        n = len(hashes)
        es = b"".join(
            self._e_bytes(bytes(pubs[i]), bytes(hashes[i])) for i in range(n)
        )
        out = native_bind.sm2_verify_batch(
            es,
            np.ascontiguousarray(rs).tobytes(),
            np.ascontiguousarray(ss).tobytes(),
            np.ascontiguousarray(pubs).tobytes(),
            n,
        )
        return None if out is None else np.asarray(out, dtype=bool)

    def batch_verify(self, msg_hashes, pubs, sigs) -> np.ndarray:
        sigs = np.asarray(sigs, dtype=np.uint8)
        hashes = np.asarray(msg_hashes, dtype=np.uint8)
        pubs = np.asarray(pubs, dtype=np.uint8)
        from ..device.plane import get_plane, plane_route, plane_wait

        if plane_route() and len(sigs):
            return plane_wait(get_plane().submit(
                "verify.sm2",
                (hashes, pubs, sigs),
                len(sigs),
                _verify_plane_exec(self),
            ))
        return self._verify_merged(hashes, pubs, sigs)

    def _verify_merged(self, hashes, pubs, sigs) -> np.ndarray:
        if use_native_batch(len(sigs)):
            out = self._native_batch_verify(
                hashes, pubs, sigs[:, :32], sigs[:, 32:64]
            )
            if out is not None:
                _note_dispatch_path("sm2_verify", "native")
                return out
        _note_dispatch_path("sm2_verify", "device")
        return _device_or_host(
            sm2_ops.verify_batch, self._host_verify_loop,
            hashes, sigs[:, :32], sigs[:, 32:64], pubs,
        )

    def _host_verify_loop(self, hashes, rs, ss, pubs) -> np.ndarray:
        """Degraded-mode fallback: per-item SM2 verify on the host."""
        return np.array(
            [
                self.verify(
                    bytes(pubs[i]),
                    bytes(hashes[i]),
                    bytes(rs[i]) + bytes(ss[i]) + bytes(pubs[i]),
                )
                for i in range(len(hashes))
            ],
            dtype=bool,
        )

    def batch_recover(self, msg_hashes, sigs):
        sigs = np.asarray(sigs, dtype=np.uint8)
        hashes = np.asarray(msg_hashes, dtype=np.uint8)
        from ..device.plane import get_plane, plane_route, plane_wait

        if plane_route() and len(sigs):
            return plane_wait(get_plane().submit(
                "recover.sm2",
                (hashes, sigs),
                len(sigs),
                _recover_plane_exec(self),
            ))
        return self._recover_merged(hashes, sigs)

    def _recover_merged(self, hashes, sigs):
        if use_native_batch(len(sigs)):
            pubs = sigs[:, 64:128]
            ok = self._native_batch_verify(
                hashes, pubs, sigs[:, :32], sigs[:, 32:64]
            )
            if ok is not None:
                _note_dispatch_path("sm2_recover", "native")
                out = np.where(ok[:, None], pubs, np.zeros_like(pubs))
                return out, ok

        def _host_recover(hashes_, sigs_):
            pubs_ = sigs_[:, 64:128]
            ok_ = self._host_verify_loop(
                hashes_, sigs_[:, :32], sigs_[:, 32:64], pubs_
            )
            return np.where(ok_[:, None], pubs_, np.zeros_like(pubs_)), ok_

        _note_dispatch_path("sm2_recover", "device")
        return _device_or_host(sm2_ops.recover_batch, _host_recover, hashes, sigs)


# ---------------------------------------------------------------------------
# The suite
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CryptoSuite:
    """Hash + signature bundle (reference: CryptoSuite.h:33-69)."""

    hash_impl: HashImpl
    signature_impl: SignatureCrypto

    def hash(self, data: bytes) -> bytes:
        return self.hash_impl.hash(data)

    def hash_batch(self, msgs) -> np.ndarray:
        return self.hash_impl.hash_batch(msgs)

    def hash_batch_async(self, msgs):
        return self.hash_impl.hash_batch_async(msgs)

    def calculate_address(self, pub: bytes) -> bytes:
        """right160(hash(pubkey)) — CryptoSuite.h:56-59."""
        return right160(self.hash_impl.hash(pub))

    def calculate_address_batch(self, pubs: np.ndarray) -> np.ndarray:
        digests = self.hash_impl.hash_batch([bytes(p) for p in np.asarray(pubs)])
        return digests[:, 12:]

    def merkle_root_async(self, leaves: np.ndarray):
        """Dispatch-now, sync-later (() -> bytes) wide device merkle over
        ``[N, 32]`` uint8 leaves, hasher chosen by this suite.

        This is the DevicePlane seam protocol/ledger callers use instead of
        importing ``ops.merkle`` directly — the device-dispatch analyzer
        rejects kernel imports outside the crypto/device/ops/parallel seams.
        """
        return merkle_ops.merkle_root_async(leaves, hasher=self.hash_impl.name)

    def merkle_tree(self, leaves: np.ndarray) -> "merkle_ops.MerkleTree":
        """Build a full proof-capable tree (every level retained) over
        ``[N, 32]`` uint8 leaves — the ProofPlane's frozen-tree builder.

        Routed through the shared DevicePlane as the ``merkle_tree`` op on
        the caller's lane (the ProofPlane submits under
        ``device_lane("proof")``, the lane below ``sync``), so cache-miss
        tree builds from a proof storm queue BEHIND consensus, admission
        and gossip batches instead of competing with them. Leaf counts are
        bucket-padded inside :class:`~fisco_bcos_tpu.ops.merkle.MerkleTree`
        (``bucket_leaves``), so the compiled-program set stays within the
        ladder. Bit-identical to a direct ``MerkleTree(...)`` build by
        construction — both paths run the same constructor.
        """
        from ..device.plane import get_plane, plane_route, plane_wait
        from ..observability.device import device_span

        leaves = np.asarray(leaves, dtype=np.uint8)
        if plane_route() and len(leaves) > 1:
            # op name carries the hasher (like `hash.<name>` / `sm2_verify`):
            # the plane binds ONE executor per op name process-wide, and a
            # multi-suite host (keccak + SM groups) must not have the first
            # suite's hasher capture every group's tree builds
            return plane_wait(get_plane().submit(
                f"merkle_tree.{self.hash_impl.name}",
                leaves,
                len(leaves),
                _merkle_tree_plane_exec(self.hash_impl.name),
            ))
        # direct path gets the same span the plane executor wraps builds
        # in — tree hashing stays attributed with the plane off too
        with device_span(
            "merkle_tree",
            len(leaves),
            shape_key=(
                self.hash_impl.name,
                merkle_ops.bucket_leaves(max(len(leaves), 1)),
            ),
        ):
            return merkle_ops.MerkleTree(leaves, hasher=self.hash_impl.name)


def _merkle_tree_plane_exec(hasher: str):
    """Plane executor for proof-tree builds: each request is its own tree
    (different heights — there is nothing sound to merge across roots), but
    dispatching them through one plane slot serializes read-path hashing
    behind the priority lanes and shares the dispatch accounting."""

    def run(reqs):
        from ..observability.device import device_span

        out = []
        for r in reqs:
            leaves = r.payload
            with device_span(
                "merkle_tree",
                len(leaves),
                shape_key=(hasher, merkle_ops.bucket_leaves(max(len(leaves), 1))),
            ):
                out.append(merkle_ops.MerkleTree(leaves, hasher=hasher))
        return out

    return run


def ecdsa_suite() -> CryptoSuite:
    """Keccak256 + secp256k1 (the reference's default, non-SM suite)."""
    return CryptoSuite(Keccak256(), Secp256k1Crypto())


def sm_suite() -> CryptoSuite:
    """SM3 + SM2 (the reference's sm_crypto=true national suite)."""
    return CryptoSuite(SM3(), SM2Crypto())
