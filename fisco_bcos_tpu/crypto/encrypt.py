"""Symmetric encryption — AES-256-CBC and SM4-CBC.

Reference: bcos-crypto/encrypt/{AESCrypto.cpp, SM4Crypto.cpp} (wedpr FFI),
consumed by bcos-security/DataEncryption.cpp.  Wire format here (and for the
DataEncryption consumer): ``iv(16) ‖ ciphertext`` with PKCS7 padding —
self-contained ciphertexts, fresh IV per encryption.

AES rides the baked-in ``cryptography`` package (OpenSSL-backed, like the
reference); SM4 uses the pure-Python block cipher in crypto/ref/sm4.py
(no tassl in this image — the host cost is per-value at rest, not on the
consensus hot path).
"""

from __future__ import annotations

import hashlib
import os

from .ref import sm4 as ref_sm4


class SymmetricEncryption:
    """bcos-framework SymmetricEncryption interface analog."""

    name = ""
    key_len = 32

    def __init__(self, key: bytes):
        if len(key) != self.key_len:
            # the reference derives fixed-size dataKeys by hashing the
            # configured passphrase (DataEncryption.cpp init)
            key = hashlib.sha256(key).digest()[: self.key_len]
        self.key = key

    def encrypt(self, plaintext: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, ciphertext: bytes) -> bytes:
        raise NotImplementedError


class AESEncryption(SymmetricEncryption):
    """AES-256-CBC with PKCS7 (AESCrypto.cpp analog)."""

    name = "aes-256-cbc"
    key_len = 32

    def encrypt(self, plaintext: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
        from cryptography.hazmat.primitives.padding import PKCS7

        iv = os.urandom(16)
        padder = PKCS7(128).padder()
        data = padder.update(plaintext) + padder.finalize()
        enc = Cipher(algorithms.AES(self.key), modes.CBC(iv)).encryptor()
        return iv + enc.update(data) + enc.finalize()

    def decrypt(self, ciphertext: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
        from cryptography.hazmat.primitives.padding import PKCS7

        iv, body = ciphertext[:16], ciphertext[16:]
        dec = Cipher(algorithms.AES(self.key), modes.CBC(iv)).decryptor()
        data = dec.update(body) + dec.finalize()
        unpadder = PKCS7(128).unpadder()
        return unpadder.update(data) + unpadder.finalize()


class SM4Encryption(SymmetricEncryption):
    """SM4-CBC with PKCS7 (SM4Crypto.cpp analog; national-secret mode).
    Native C blocks when available (native_bind); pure-Python fallback."""

    name = "sm4-cbc"
    key_len = 16

    def encrypt(self, plaintext: bytes) -> bytes:
        from .. import native_bind

        iv = os.urandom(16)
        padded = ref_sm4._pad(plaintext)
        out = native_bind.sm4_cbc(self.key, iv, padded, decrypt=False)
        if out is None:
            out = ref_sm4.cbc_encrypt(self.key, iv, plaintext)
        return iv + out

    def decrypt(self, ciphertext: bytes) -> bytes:
        from .. import native_bind

        iv, body = ciphertext[:16], ciphertext[16:]
        out = native_bind.sm4_cbc(self.key, iv, body, decrypt=True)
        if out is None:
            return ref_sm4.cbc_decrypt(self.key, iv, body)
        return ref_sm4._unpad(out)


def make_encryption(key: bytes, sm_crypto: bool = False) -> SymmetricEncryption:
    """Suite selection mirrors ProtocolInitializer.cpp:51-99: sm_crypto
    deployments pair SM3/SM2 with SM4; standard with AES."""
    return SM4Encryption(key) if sm_crypto else AESEncryption(key)
