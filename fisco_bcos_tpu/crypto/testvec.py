"""Deterministic signed-payload vector generation (bench + graft entry).

Signs a small unique payload set with fixed keys and tiles it to the target
batch — the same trick the reference's TPS harness uses
(bcos-rpc DuplicateTransactionFactory.cpp duplicates one signed tx N×).
"""

from __future__ import annotations

import numpy as np

from ..ops.bigint import bytes_be_to_limbs
from ..ops.hash_common import pad_keccak
from .ref import ecdsa as ref_ecdsa
from .ref.keccak import keccak256


def signed_payload_vectors(
    n: int,
    unique: int = 8,
    payload_fn=lambda i: b"fisco-bcos-tpu vector tx %06d" % i,
    secret_fn=lambda i: 0xC0FFEE + 7919 * i,
):
    """-> (payloads list[bytes] len n, sigs65 [n, 65] uint8, digests, pubs),
    with `unique` distinct signers/payloads tiled to n."""
    unique = min(n, unique)
    payloads, sigs, digests, pubs = [], [], [], []
    for i in range(unique):
        payload = payload_fn(i)
        d = secret_fn(i)
        h = keccak256(payload)
        r, s, v = ref_ecdsa.ecdsa_sign(h, d)
        payloads.append(payload)
        digests.append(h)
        sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([v]))
        pubs.append(ref_ecdsa.privkey_to_pubkey(ref_ecdsa.SECP256K1, d))
    reps = -(-n // unique)
    payloads = (payloads * reps)[:n]
    sigs65 = np.frombuffer(b"".join(sigs * reps), dtype=np.uint8).reshape(-1, 65)[:n]
    return payloads, sigs65, digests, pubs


def admission_tensors(payloads, sigs65):
    """Host-padded device tensors for crypto.admission.admission_step:
    (blocks, nblocks, r, s, v) as numpy arrays."""
    n = len(payloads)
    # pad_keccak buckets the batch dim; this helper's contract is
    # exact-size tensors (mesh dryruns shard on the true batch), so slice
    blocks, nblocks = pad_keccak(payloads)
    blocks, nblocks = blocks[:n], nblocks[:n]
    sigs65 = np.asarray(sigs65, dtype=np.uint8)
    r = bytes_be_to_limbs(sigs65[:, :32])
    s = bytes_be_to_limbs(sigs65[:, 32:64])
    v = sigs65[:, 64].astype(np.int32)
    return blocks, nblocks, r, s, v
