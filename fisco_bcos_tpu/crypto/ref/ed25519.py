"""Ed25519 (RFC 8032) — pure-Python reference implementation.

Reference role: bcos-crypto/signature/ed25519/Ed25519Crypto.cpp (wedpr FFI).
Ed25519 is a secondary suite in the reference (consortium deployments sign
txs with secp256k1 or SM2); here it is host-side only — the batch device
plane covers the two tx-signing curves, and this module keeps interface
parity for the remaining signature surface.

Textbook RFC 8032 math: edwards25519 in extended homogeneous coordinates,
SHA-512 from hashlib, little-endian point compression with the x-parity bit.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, -1, P)) % P

_BY = 4 * pow(5, -1, P) % P
_BX = None  # derived below


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


def _recover_x(y: int, sign: int) -> int | None:
    """x from y via the curve equation; None if y is off-curve."""
    x2 = (y * y - 1) * _inv(D * y * y + 1) % P
    if x2 == 0:
        return 0 if sign == 0 else None
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * pow(2, (P - 1) // 4, P) % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
BASE = (_BX, _BY, 1, _BX * _BY % P)  # extended (X, Y, Z, T)
IDENT = (0, 1, 1, 0)


def _add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return e * f % P, g * h % P, f * g % P, e * h % P


def _mul(s: int, p):
    q = IDENT
    while s > 0:
        if s & 1:
            q = _add(q, p)
        p = _add(p, p)
        s >>= 1
    return q


def _compress(p) -> bytes:
    x, y, z, _ = p
    zi = _inv(z)
    x, y = x * zi % P, y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(data: bytes):
    if len(data) != 32:
        return None
    yv = int.from_bytes(data, "little")
    sign = yv >> 255
    yv &= (1 << 255) - 1
    if yv >= P:
        return None
    x = _recover_x(yv, sign)
    if x is None:
        return None
    return (x, yv, 1, x * yv % P)


def _eq_points(p, q) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def seed_to_pubkey(seed: bytes) -> bytes:
    """32-byte seed -> 32-byte compressed public key."""
    a = _clamp(_sha512(seed))
    return _compress(_mul(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    h = _sha512(seed)
    a = _clamp(h)
    prefix = h[32:]
    apub = _compress(_mul(a, BASE))
    r = int.from_bytes(_sha512(prefix + msg), "little") % L
    rpt = _compress(_mul(r, BASE))
    k = int.from_bytes(_sha512(rpt + apub + msg), "little") % L
    s = (r + k * a) % L
    return rpt + s.to_bytes(32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != 64:
        return False
    a_pt = _decompress(pub)
    r_pt = _decompress(sig[:32])
    if a_pt is None or r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False  # malleability guard (RFC 8032 §5.1.7)
    k = int.from_bytes(_sha512(sig[:32] + pub + msg), "little") % L
    # 8*S*B == 8*R + 8*k*A (cofactored verification)
    lhs = _mul(8 * s, BASE)
    rhs = _add(_mul(8, r_pt), _mul(8 * k % (8 * L), a_pt))
    return _eq_points(lhs, rhs)
