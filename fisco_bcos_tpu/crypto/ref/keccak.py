"""Pure-Python Keccak-256 (legacy 0x01 padding, as used for Ethereum-style tx
hashing in the reference's Keccak256 hasher — bcos-crypto hash/Keccak256.h).

NIST SHA3-256 differs only in the domain-separation padding byte (0x06)."""

from __future__ import annotations

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# Rotation offsets r[x][y] (x = column, y = row), lane index = x + 5*y.
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK = (1 << 64) - 1


def _rotl(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK


def keccak_f1600(state: list[int]) -> list[int]:
    """24-round Keccak-f[1600] permutation over 25 lanes (index = x + 5y)."""
    A = list(state)
    for rc in _RC:
        # theta
        C = [A[x] ^ A[x + 5] ^ A[x + 10] ^ A[x + 15] ^ A[x + 20] for x in range(5)]
        D = [C[(x - 1) % 5] ^ _rotl(C[(x + 1) % 5], 1) for x in range(5)]
        A = [A[i] ^ D[i % 5] for i in range(25)]
        # rho + pi: B[y, 2x+3y] = rot(A[x, y])
        B = [0] * 25
        for x in range(5):
            for y in range(5):
                B[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(A[x + 5 * y], _ROT[x][y])
        # chi
        A = [
            B[x + 5 * y] ^ ((~B[(x + 1) % 5 + 5 * y]) & _MASK & B[(x + 2) % 5 + 5 * y])
            for y in range(5)
            for x in range(5)
        ]
        # iota
        A[0] ^= rc
    return A


def _keccak(data: bytes, rate: int, out_len: int, pad_byte: int) -> bytes:
    state = [0] * 25
    # multi-rate padding
    padded = bytearray(data)
    padded.append(pad_byte)
    while len(padded) % rate:
        padded.append(0)
    padded[-1] |= 0x80
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        state = keccak_f1600(state)
    out = b"".join(state[i].to_bytes(8, "little") for i in range(out_len // 8))
    return out[:out_len]


def keccak256(data: bytes) -> bytes:
    return _keccak(data, rate=136, out_len=32, pad_byte=0x01)


def sha3_256(data: bytes) -> bytes:
    return _keccak(data, rate=136, out_len=32, pad_byte=0x06)
