"""SHA-256 reference (stdlib-backed; kept behind one name so golden tests and
suites import from a single place)."""

from __future__ import annotations

import hashlib


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()
