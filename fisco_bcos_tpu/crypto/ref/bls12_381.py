"""Pure-Python BLS12-381 reference: tower fields, curves, optimal-ate
pairing, aggregate BLS signatures — the host/correctness anchor for the
device kernels in :mod:`fisco_bcos_tpu.ops.bls12_381`.

This is the QC subsystem's bit-exact ground truth (the role
crypto/ref/ed25519.py plays for the Ed25519 plane): single-item sign /
verify / aggregate run here, and the jitted pairing kernel is pinned
against these functions in tests. Design choices made for verifiability
over cleverness:

- **Fp12 in one polynomial basis.** Fp12 = Fp[w]/(w^12 - 2w^6 + 2)
  (w^6 = 1 + u, u^2 = -1 — the standard tower flattened), so
  multiplication is generic polynomial arithmetic and inversion is the
  extended Euclid over Fp[w]: no hand-copied tower inversion formulas on
  the reference path. The device kernel uses the Karatsuba tower; tests
  cross-check it against this basis through the (trivial) change-of-basis.
- **Miller loop with the G2 accumulator on the twist.** T stays in
  affine Fp2 on E': y^2 = x^3 + 4(1+u); the line through untwisted points
  is assembled directly in its sparse w-basis form (coefficients at
  w^0/w^2/w^3 after the w^3 normalization — every normalization factor
  lies in a subfield of Fp12 killed by the final exponentiation, the
  standard denominator-elimination argument).
- **Hard part by the BLS12 chain, verified symbolically.** The exponent
  identity 3(p^4-p^2+1)/r = (x-1)^2 (x+p) (x^2+p^2-1) + 3 is asserted
  over Python ints at import; exponentiating by the 3x multiple is sound
  because GT has prime order r != 3 (cubing is a bijection). Conjugation
  serves as inversion only after the easy part (cyclotomic subgroup).
- **Derived, not transcribed, group orders.** The G2 cofactor is found
  by testing the six possible twist orders against sample curve points
  (exact integer arithmetic, cached) instead of pasting a 500-bit
  constant; the published h1 = (x-1)^2/3 is functionally asserted before
  use. A memory-slip in a magic number can't ship silently.
- **hash-to-G2 is deterministic try-and-increment** (SHA-256 counter
  expansion, complex-method Fp2 sqrt, cofactor clearing) — NOT RFC 9380
  SSWU: this chain defines its own QC wire format and needs determinism
  and uniform committee agreement, not cross-ecosystem interop. The
  isogeny constant tables RFC 9380 needs are exactly the kind of
  transcription this module refuses to depend on. Swapping in SSWU later
  only changes this one function.

Scheme: minimal-pubkey-size BLS (pubkeys in G1, 48-byte compressed;
signatures in G2, 96-byte compressed), same-message aggregation — the
quorum-certificate case where every vote signs one header hash, so one
pairing check e(g1, agg_sig) == e(agg_pk, H(m)) admits the whole quorum.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_PARAM = -0xD201000000010000  # the (negative) BLS parameter x
B_G1 = 4  # E:  y^2 = x^3 + 4
B_G2 = (4, 4)  # E': y^2 = x^3 + 4(1+u)

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# the hard-part identity the final exponentiation chain implements; if it
# ever failed the chain below would be silently wrong, so it is proved
# over exact ints before anything imports far enough to call pairing()
assert (
    (X_PARAM - 1) ** 2 * (X_PARAM + P) * (X_PARAM**2 + P**2 - 1) + 3
    == 3 * ((P**4 - P**2 + 1) // R_ORDER)
), "BLS12 hard-part exponent decomposition does not hold"
assert P % 4 == 3  # Fp sqrt via a^((p+1)/4)
assert (P - 1) % 6 == 0

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------


def fp_inv(a: int) -> int:
    return pow(a, P - 2, P) if a else 0


def fp_legendre(a: int) -> int:
    """1 for QR, -1 for non-residue, 0 for 0."""
    if a % P == 0:
        return 0
    return 1 if pow(a, (P - 1) // 2, P) == 1 else -1


def fp_sqrt(a: int) -> int | None:
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a % P else None


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2 + 1) — tuples (c0, c1)
# ---------------------------------------------------------------------------

F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (1, 1)  # the sextic non-residue 1 + u (w^6 = XI)


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return (-a[0] % P, -a[1] % P)


def f2_mul(a, b):
    v0 = a[0] * b[0] % P
    v1 = a[1] * b[1] % P
    c1 = ((a[0] + a[1]) * (b[0] + b[1]) - v0 - v1) % P
    return ((v0 - v1) % P, c1)


def f2_sqr(a):
    return f2_mul(a, a)


def f2_muli(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def f2_inv(a):
    n = (a[0] * a[0] + a[1] * a[1]) % P
    ni = fp_inv(n)
    return (a[0] * ni % P, -a[1] * ni % P)


def f2_conj(a):
    return (a[0], -a[1] % P)


def f2_is_zero(a) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


def f2_sqrt(a):
    """Complex-method square root in Fp2 (p ≡ 3 mod 4); None when `a` is
    a non-residue. The candidate is always re-checked by squaring, so a
    wrong branch can only return None, never a bad root."""
    a = (a[0] % P, a[1] % P)
    if a == F2_ZERO:
        return F2_ZERO
    if a[1] == 0:
        r = fp_sqrt(a[0])
        if r is not None:
            return (r, 0)
        r = fp_sqrt(-a[0] % P)  # (u*t)^2 = -t^2
        return (0, r) if r is not None else None
    n = (a[0] * a[0] + a[1] * a[1]) % P
    alpha = fp_sqrt(n)
    if alpha is None:
        return None
    inv2 = fp_inv(2)
    for al in (alpha, -alpha % P):
        delta = (a[0] + al) * inv2 % P
        x0 = fp_sqrt(delta)
        if x0 is None or x0 == 0:
            continue
        x1 = a[1] * fp_inv(2 * x0 % P) % P
        cand = (x0, x1)
        if f2_sqr(cand) == a:
            return cand
    return None


def f2_sign(a) -> int:
    """Deterministic sign for compression: 1 when `a` is the
    lexicographically larger of {a, -a} (c1 first, then c0)."""
    if a[1] % P != 0:
        return 1 if a[1] % P > (P - 1) // 2 else 0
    return 1 if a[0] % P > (P - 1) // 2 else 0


# ---------------------------------------------------------------------------
# Short-Weierstrass affine groups over a pluggable field (Fp and Fp2)
# ---------------------------------------------------------------------------
# Points are (x, y) tuples or None for infinity. A field is described by a
# small ops record so ONE set of formulas serves both curves — formula
# duplication is how sign errors creep in.


class _FieldOps:
    __slots__ = ("add", "sub", "mul", "sqr", "inv", "neg", "muli", "b")

    def __init__(self, add, sub, mul, sqr, inv, neg, muli, b):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.inv, self.neg, self.muli, self.b = inv, neg, muli, b


FP_OPS = _FieldOps(
    add=lambda a, b: (a + b) % P,
    sub=lambda a, b: (a - b) % P,
    mul=lambda a, b: a * b % P,
    sqr=lambda a: a * a % P,
    inv=fp_inv,
    neg=lambda a: -a % P,
    muli=lambda a, k: a * k % P,
    b=B_G1,
)

FP2_OPS = _FieldOps(
    add=f2_add,
    sub=f2_sub,
    mul=f2_mul,
    sqr=f2_sqr,
    inv=f2_inv,
    neg=f2_neg,
    muli=f2_muli,
    b=B_G2,
)


def ec_neg(pt, F: _FieldOps):
    return None if pt is None else (pt[0], F.neg(pt[1]))


def ec_double(pt, F: _FieldOps):
    if pt is None:
        return None
    x, y = pt
    lam = F.mul(F.muli(F.sqr(x), 3), F.inv(F.muli(y, 2)))
    x3 = F.sub(F.sqr(lam), F.muli(x, 2))
    y3 = F.sub(F.mul(lam, F.sub(x, x3)), y)
    return (x3, y3)


def ec_add(p1, p2, F: _FieldOps):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return ec_double(p1, F)
        return None  # p2 == -p1
    lam = F.mul(F.sub(y2, y1), F.inv(F.sub(x2, x1)))
    x3 = F.sub(F.sub(F.sqr(lam), x1), x2)
    y3 = F.sub(F.mul(lam, F.sub(x1, x3)), y1)
    return (x3, y3)


def ec_mul_affine(pt, k: int, F: _FieldOps):
    """Plain affine double-and-add — the slow, obviously-correct ladder
    the Jacobian fast path is differential-tested against."""
    if k < 0:
        return ec_mul_affine(ec_neg(pt, F), -k, F)
    acc = None
    while k:
        if k & 1:
            acc = ec_add(acc, pt, F)
        pt = ec_double(pt, F)
        k >>= 1
    return acc


def _jac_double(X, Y, Z, F: _FieldOps):
    """dbl-2009-l (a = 0): 2M + 5S, inversion-free."""
    A = F.sqr(X)
    B = F.sqr(Y)
    C = F.sqr(B)
    D = F.muli(F.sub(F.sub(F.sqr(F.add(X, B)), A), C), 2)
    E = F.muli(A, 3)
    X3 = F.sub(F.sqr(E), F.muli(D, 2))
    Y3 = F.sub(F.mul(E, F.sub(D, X3)), F.muli(C, 8))
    Z3 = F.muli(F.mul(Y, Z), 2)
    return X3, Y3, Z3


def _jac_add_affine(X, Y, Z, x2, y2, F: _FieldOps):
    """madd-2007-bl mixed addition; falls back to doubling / infinity on
    the equal-x edge cases."""
    zero = F.sub(X, X)
    Z1Z1 = F.sqr(Z)
    U2 = F.mul(x2, Z1Z1)
    S2 = F.mul(F.mul(y2, Z), Z1Z1)
    H = F.sub(U2, X)
    r = F.muli(F.sub(S2, Y), 2)
    if H == zero:
        if r == zero:
            return _jac_double(X, Y, Z, F)
        return X, Y, zero  # P + (-P) = infinity
    HH = F.sqr(H)
    I = F.muli(HH, 4)
    J = F.mul(H, I)
    V = F.mul(X, I)
    X3 = F.sub(F.sub(F.sqr(r), J), F.muli(V, 2))
    Y3 = F.sub(F.mul(r, F.sub(V, X3)), F.muli(F.mul(Y, J), 2))
    Z3 = F.sub(F.sub(F.sqr(F.add(Z, H)), Z1Z1), HH)
    return X3, Y3, Z3


def ec_mul(pt, k: int, F: _FieldOps):
    """Scalar multiplication via Jacobian double-and-add (one inversion at
    the end) — bit-identical in result to :func:`ec_mul_affine`, which
    tests pin."""
    if pt is None or k == 0:
        return None
    if k < 0:
        return ec_mul(ec_neg(pt, F), -k, F)
    x2, y2 = pt
    one = 1 if isinstance(x2, int) else F2_ONE
    zero = F.sub(x2, x2)
    X = Y = Z = None
    for bit in bin(k)[2:]:
        if X is not None:
            X, Y, Z = _jac_double(X, Y, Z, F)
        if bit == "1":
            if X is None:
                X, Y, Z = x2, y2, one  # affine seed, Z = 1
            elif Z == zero:
                X, Y, Z = x2, y2, one  # re-seed after P + (-P)
            else:
                X, Y, Z = _jac_add_affine(X, Y, Z, x2, y2, F)
    if Z == zero:
        return None
    zi = F.inv(Z)
    zi2 = F.sqr(zi)
    return F.mul(X, zi2), F.mul(Y, F.mul(zi, zi2))


def ec_on_curve(pt, F: _FieldOps) -> bool:
    if pt is None:
        return True
    x, y = pt
    if isinstance(F.b, int):  # Fp
        return y * y % P == (x * x % P * x + F.b) % P
    return F.sqr(y) == F.add(F.mul(F.sqr(x), x), F.b)


G1 = (G1_X, G1_Y)
G2 = (G2_X, G2_Y)
assert ec_on_curve(G1, FP_OPS), "G1 generator not on E"
assert ec_on_curve(G2, FP2_OPS), "G2 generator not on E'"


# ---------------------------------------------------------------------------
# Group orders / cofactors — derived, then functionally asserted
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def g1_cofactor() -> int:
    """h1 = (x-1)^2 / 3 (standard BLS12 fact), asserted against the curve:
    the full order h1*r must annihilate the generator."""
    h1, rem = divmod((X_PARAM - 1) ** 2, 3)
    assert rem == 0
    assert ec_mul(G1, h1 * R_ORDER, FP_OPS) is None, "G1 order formula wrong"
    return h1


@lru_cache(maxsize=None)
def g2_cofactor() -> int:
    """#E'(Fp2) / r, found by testing the six possible sextic-twist orders
    against sample twist points (exact arithmetic — no transcribed 500-bit
    constant to get wrong)."""
    import math

    n1 = g1_cofactor() * R_ORDER  # #E(Fp)
    t = P + 1 - n1  # Frobenius trace over Fp
    t2 = t * t - 2 * P  # trace over Fp2
    v2sq, rem = divmod(4 * P * P - t2 * t2, 3)
    assert rem == 0
    v2 = math.isqrt(v2sq)
    assert v2 * v2 == v2sq, "twist discriminant is not a perfect square"
    candidates = [P * P + 1 - t2, P * P + 1 + t2]
    for s_num in (t2 + 3 * v2, t2 - 3 * v2):
        if s_num % 2 == 0:  # only integral traces are candidates
            candidates += [P * P + 1 - s_num // 2, P * P + 1 + s_num // 2]
    samples = [_curve_point_g2(b"fisco-bls-order-probe-%d" % i) for i in (0, 1)]
    for n in candidates:
        if all(ec_mul(q, n, FP2_OPS) is None for q in samples):
            h2, rem = divmod(n, R_ORDER)
            assert rem == 0, "twist order not divisible by r"
            assert ec_mul(G2, n, FP2_OPS) is None
            return h2
    raise AssertionError("no candidate twist order annihilates E'(Fp2)")


def _expand(tag: bytes, msg: bytes, ctr: int) -> tuple[int, int]:
    """Deterministic (c0, c1) Fp2 x-candidate from SHA-256 counter blocks."""
    digs = [
        hashlib.sha256(tag + bytes([ctr, j]) + msg).digest() for j in range(4)
    ]
    c0 = int.from_bytes(digs[0] + digs[1], "big") % P
    c1 = int.from_bytes(digs[2] + digs[3], "big") % P
    return (c0, c1)


def _curve_point_g2(msg: bytes, tag: bytes = b"FISCO-BLS12381-G2-TAI:"):
    """Deterministic point on E'(Fp2) (NOT cofactor-cleared): smallest
    counter whose x-candidate lands on the curve."""
    for ctr in range(256):
        x = _expand(tag, msg, ctr)
        rhs = f2_add(f2_mul(f2_sqr(x), x), XI_B)
        y = f2_sqrt(rhs)
        if y is None:
            continue
        # canonical root: sign bit 0 (deterministic across implementations)
        if f2_sign(y):
            y = f2_neg(y)
        return (x, y)
    raise AssertionError("no curve point within 256 counters")  # p(fail)≈2^-256


XI_B = (4, 4)  # b' = 4 * (1 + u)


@lru_cache(maxsize=4096)
def hash_to_g2(msg: bytes):
    """Deterministic hash-to-G2: try-and-increment onto E'(Fp2), then
    cofactor clearing into the r-torsion. Cached: consensus signs/verifies
    the same header hash from every committee member."""
    pt = _curve_point_g2(msg)
    out = ec_mul(pt, g2_cofactor(), FP2_OPS)
    assert out is not None  # a curve point of full cofactor order would be
    return out


def subgroup_check_g1(pt) -> bool:
    return ec_on_curve(pt, FP_OPS) and ec_mul(pt, R_ORDER, FP_OPS) is None


def subgroup_check_g2(pt) -> bool:
    return ec_on_curve(pt, FP2_OPS) and ec_mul(pt, R_ORDER, FP2_OPS) is None


# ---------------------------------------------------------------------------
# Fp12 = Fp[w]/(w^12 - 2 w^6 + 2) — coefficient lists of 12 ints
# ---------------------------------------------------------------------------

F12_ONE = (1,) + (0,) * 11
F12_ZERO = (0,) * 12


def f12_from_fp2(c, k: int = 0):
    """Embed c = c0 + c1*u at basis position w^k: u = w^6 - 1, so the
    element is (c0 - c1)*w^k + c1*w^(k+6)."""
    out = [0] * 12
    out[k] = (c[0] - c[1]) % P
    out[k + 6] = c[1] % P
    return tuple(out)


def f12_mul(a, b):
    t = [0] * 23
    for i, ai in enumerate(a):
        if ai:
            for j, bj in enumerate(b):
                t[i + j] += ai * bj
    # w^12 = 2 w^6 - 2
    for k in range(22, 11, -1):
        c = t[k]
        if c:
            t[k - 6] += 2 * c
            t[k - 12] -= 2 * c
    return tuple(v % P for v in t[:12])


def f12_sqr(a):
    return f12_mul(a, a)


def f12_neg(a):
    return tuple(-v % P for v in a)


def f12_muli(a, k: int):
    return tuple(v * k % P for v in a)


def f12_pow(a, e: int):
    if e < 0:
        return f12_pow(f12_inv(a), -e)
    out = F12_ONE
    while e:
        if e & 1:
            out = f12_mul(out, a)
        a = f12_sqr(a)
        e >>= 1
    return out


_W_POLY = (2, 0, 0, 0, 0, 0, -2, 0, 0, 0, 0, 0, 1)  # w^12 - 2w^6 + 2 (low→high)


def f12_inv(a):
    """Extended Euclid over Fp[w] modulo the defining polynomial — generic
    algebra, no tower inversion formulas to mistranscribe."""

    def pdiv(num, den):
        num = list(num)
        deg_d = max(i for i, v in enumerate(den) if v)
        inv_lead = fp_inv(den[deg_d])
        q = [0] * (len(num))
        for k in range(len(num) - 1, deg_d - 1, -1):
            if num[k] % P == 0:
                continue
            f = num[k] * inv_lead % P
            q[k - deg_d] = f
            for i, dv in enumerate(den[: deg_d + 1]):
                num[k - deg_d + i] = (num[k - deg_d + i] - f * dv) % P
        return q, [v % P for v in num[: deg_d if deg_d else 1]]

    # gcd(a, W) with Bezout tracking: s*a ≡ gcd (mod W)
    r0 = [v % P for v in _W_POLY]
    r1 = list(a) + [0]
    s0, s1 = [0], [1]
    while any(v % P for v in r1):
        q, rem = pdiv(r0, r1)
        r0, r1 = r1, rem + [0] * (len(r1) - len(rem))
        # s0 - q*s1
        prod = [0] * (len(q) + len(s1))
        for i, qv in enumerate(q):
            if qv:
                for j, sv in enumerate(s1):
                    prod[i + j] = (prod[i + j] + qv * sv) % P
        ns = [
            ((s0[i] if i < len(s0) else 0) - prod[i]) % P
            for i in range(max(len(s0), len(prod)))
        ]
        s0, s1 = s1, ns
    deg = max(i for i, v in enumerate(r0) if v % P)
    assert deg == 0, "input not invertible"
    scale = fp_inv(r0[0])
    out = [v * scale % P for v in s0[:12]] + [0] * max(0, 12 - len(s0))
    # s0 may exceed degree 11 before reduction: fold through the modulus
    extra = [v * scale % P for v in s0[12:]]
    full = list(out[:12]) + extra
    for k in range(len(full) - 1, 11, -1):
        c = full[k]
        if c:
            full[k - 6] = (full[k - 6] + 2 * c) % P
            full[k - 12] = (full[k - 12] - 2 * c) % P
    return tuple(v % P for v in full[:12])


@lru_cache(maxsize=None)
def frob_table(k: int):
    """(w^i)^(p^k) for i = 0..11, each as an Fp12 element — the Frobenius
    is Fp-linear (coefficients are Frobenius-fixed), so applying it is one
    constant matrix-vector product."""
    wp = f12_pow(tuple([0, 1] + [0] * 10), pow(P, k))
    out = [F12_ONE]
    for _ in range(11):
        out.append(f12_mul(out[-1], wp))
    return tuple(out)


def f12_frob(a, k: int):
    tab = frob_table(k)
    acc = F12_ZERO
    for i, ci in enumerate(a):
        if ci:
            acc = tuple(
                (av + ci * tv) % P for av, tv in zip(acc, tab[i])
            )
    return acc


# ---------------------------------------------------------------------------
# Optimal-ate pairing
# ---------------------------------------------------------------------------


def _line_sparse(lam, xt, yt, px: int, py: int):
    """The line through (un)twisted points, normalized by w^3: with the
    slope lam computed ON THE TWIST (Fp2), the line evaluated at the
    G1 point (px, py) is

        l * w^3 = (lam*xt - yt)  +  (-lam*px) w^2  +  py w^3

    (all normalization factors lie in killed subfields). Returned dense in
    the w-basis."""
    c0 = f2_sub(f2_mul(lam, xt), yt)  # Fp2 at w^0
    c2 = f2_muli(lam, -px % P)  # Fp2 * px at w^2
    out = [0] * 12
    out[0] = (c0[0] - c0[1]) % P
    out[6] = c0[1]
    out[2] = (c2[0] - c2[1]) % P
    out[8] = c2[1]
    out[3] = py % P
    return tuple(out)


def miller_loop(pairs) -> tuple:
    """Product of Miller loops f_{|x|, Qi}(Pi) for [(P_g1, Q_g2twist)]
    pairs, conjugated for the negative parameter. Accumulators stay in
    affine Fp2 on the twist; slopes cost one Fp2 inversion per step."""
    bits = bin(-X_PARAM)[2:]
    f = F12_ONE
    ts = [q for _, q in pairs]
    for bit in bits[1:]:
        f = f12_sqr(f)
        for i, (p1, _q) in enumerate(pairs):
            t = ts[i]
            lam = f2_mul(
                f2_muli(f2_sqr(t[0]), 3), f2_inv(f2_muli(t[1], 2))
            )
            f = f12_mul(f, _line_sparse(lam, t[0], t[1], p1[0], p1[1]))
            ts[i] = ec_double(t, FP2_OPS)
        if bit == "1":
            for i, (p1, q) in enumerate(pairs):
                t = ts[i]
                lam = f2_mul(
                    f2_sub(q[1], t[1]), f2_inv(f2_sub(q[0], t[0]))
                )
                f = f12_mul(f, _line_sparse(lam, t[0], t[1], p1[0], p1[1]))
                ts[i] = ec_add(t, q, FP2_OPS)
    return f12_frob(f, 6)  # x < 0: f ← f^(p^6) = conjugation


def _cyclo_pow_abs_x(a):
    """a^|x| for the cyclotomic-subgroup element a (plain square-multiply
    over the 64 static bits of |x|)."""
    out = None
    for bit in bin(-X_PARAM)[2:]:
        out = f12_sqr(out) if out is not None else None
        if out is None:
            out = a
            continue
        if bit == "1":
            out = f12_mul(out, a)
    return out


def final_exponentiation(f):
    """f^((p^12-1)/r) up to a fixed cube (see module docstring): easy part
    (p^6-1)(p^2+1), then the chain for 3(p^4-p^2+1)/r."""
    # easy part — after this, m is in the cyclotomic subgroup where
    # inversion is the p^6-Frobenius (conjugation)
    m = f12_mul(f12_frob(f, 6), f12_inv(f))
    m = f12_mul(f12_frob(m, 2), m)
    conj = lambda z: f12_frob(z, 6)  # noqa: E731 — cyclotomic inverse
    a1 = _cyclo_pow_abs_x(m)  # m^|x|
    mx2 = _cyclo_pow_abs_x(a1)  # m^(x^2)
    g = f12_mul(f12_mul(mx2, f12_sqr(a1)), m)  # m^((x-1)^2) (x<0: -2x=2|x|)
    h = f12_mul(conj(_cyclo_pow_abs_x(g)), f12_frob(g, 1))  # g^(x+p)
    hx2 = _cyclo_pow_abs_x(_cyclo_pow_abs_x(h))  # h^(x^2)
    k = f12_mul(f12_mul(hx2, f12_frob(h, 2)), conj(h))  # h^(x^2+p^2-1)
    return f12_mul(k, f12_mul(f12_sqr(m), m))  # k * m^3


def pairing_check(pairs) -> bool:
    """True iff Π e(Pi, Qi) == 1 for affine pairs (P in E(Fp), Q on the
    twist E'(Fp2)); infinity entries contribute the identity."""
    live = [(p, q) for p, q in pairs if p is not None and q is not None]
    if not live:
        return True
    return final_exponentiation(miller_loop(live)) == F12_ONE


def pairing(p1, q2):
    """e(P, Q) up to the fixed final-exponentiation cube — consistent for
    equality comparisons, which is all consensus needs."""
    if p1 is None or q2 is None:
        return F12_ONE
    return final_exponentiation(miller_loop([(p1, q2)]))


# ---------------------------------------------------------------------------
# Point compression (48-byte G1 / 96-byte G2, zcash-style header bits)
# ---------------------------------------------------------------------------

_FLAG_COMPRESSED = 0x80
_FLAG_INFINITY = 0x40
_FLAG_SIGN = 0x20


def compress_g1(pt) -> bytes:
    if pt is None:
        return bytes([_FLAG_COMPRESSED | _FLAG_INFINITY]) + b"\x00" * 47
    x, y = pt
    flags = _FLAG_COMPRESSED | (_FLAG_SIGN if y > (P - 1) // 2 else 0)
    raw = bytearray(x.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def decompress_g1(buf: bytes):
    """48 bytes -> point; raises ValueError on malformed/off-curve/out-of-
    subgroup input (deserialization is the trust boundary)."""
    if len(buf) != 48 or not buf[0] & _FLAG_COMPRESSED:
        raise ValueError("bad G1 encoding")
    if buf[0] & _FLAG_INFINITY:
        if any(buf[1:]) or buf[0] & ~(_FLAG_COMPRESSED | _FLAG_INFINITY):
            raise ValueError("bad G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([buf[0] & 0x1F]) + buf[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = fp_sqrt((x * x % P * x + B_G1) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if bool(buf[0] & _FLAG_SIGN) != (y > (P - 1) // 2):
        y = -y % P
    pt = (x, y)
    if not subgroup_check_g1(pt):
        raise ValueError("G1 point not in the r-torsion subgroup")
    return pt


def compress_g2(pt) -> bytes:
    if pt is None:
        return bytes([_FLAG_COMPRESSED | _FLAG_INFINITY]) + b"\x00" * 95
    (x0, x1), y = pt
    flags = _FLAG_COMPRESSED | (_FLAG_SIGN if f2_sign(y) else 0)
    raw = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def decompress_g2(buf: bytes):
    if len(buf) != 96 or not buf[0] & _FLAG_COMPRESSED:
        raise ValueError("bad G2 encoding")
    if buf[0] & _FLAG_INFINITY:
        if any(buf[1:]) or buf[0] & ~(_FLAG_COMPRESSED | _FLAG_INFINITY):
            raise ValueError("bad G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([buf[0] & 0x1F]) + buf[1:48], "big")
    x0 = int.from_bytes(buf[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(f2_sqr(x), x), XI_B))
    if y is None:
        raise ValueError("G2 x not on twist")
    if bool(buf[0] & _FLAG_SIGN) != bool(f2_sign(y)):
        y = f2_neg(y)
    pt = (x, y)
    if not subgroup_check_g2(pt):
        raise ValueError("G2 point not in the r-torsion subgroup")
    return pt


# ---------------------------------------------------------------------------
# The signature scheme (min-pubkey-size, same-message aggregation)
# ---------------------------------------------------------------------------


def keygen(secret: int):
    """secret int -> (sk, 48-byte compressed pubkey). sk = secret mod r,
    clamped away from 0."""
    sk = secret % R_ORDER or 1
    return sk, compress_g1(ec_mul(G1, sk, FP_OPS))


def sign(sk: int, msg: bytes) -> bytes:
    return compress_g2(ec_mul(hash_to_g2(msg), sk, FP2_OPS))


def verify(pub48: bytes, msg: bytes, sig96: bytes) -> bool:
    try:
        pk = decompress_g1(pub48)
        s = decompress_g2(sig96)
    except ValueError:
        return False
    if pk is None or s is None:
        return False  # infinity pubkey/signature is degenerate, reject
    # e(g1, sig) == e(pk, H(m))  <=>  e(-g1, sig) * e(pk, H(m)) == 1
    return pairing_check(
        [(ec_neg(G1, FP_OPS), s), (pk, hash_to_g2(msg))]
    )


def aggregate_signatures(sigs: list[bytes]) -> bytes:
    acc = None
    for s in sigs:
        acc = ec_add(acc, decompress_g2(s), FP2_OPS)
    return compress_g2(acc)


def aggregate_pubkeys(pubs: list[bytes]) -> bytes:
    acc = None
    for p in pubs:
        acc = ec_add(acc, decompress_g1(p), FP_OPS)
    return compress_g1(acc)


def aggregate_verify(pubs: list[bytes], msg: bytes, agg_sig96: bytes) -> bool:
    """Same-message aggregate verification: one pairing check for the whole
    signer set. Rogue-key safety comes from the committee registration
    model (qc pubkeys are registered with the consensus committee =
    proof-of-possession trust), not from message separation."""
    if not pubs:
        return False
    try:
        apk = decompress_g1(aggregate_pubkeys(pubs))
        s = decompress_g2(agg_sig96)
    except ValueError:
        return False
    if apk is None or s is None:
        return False
    return pairing_check([(ec_neg(G1, FP_OPS), s), (apk, hash_to_g2(msg))])
