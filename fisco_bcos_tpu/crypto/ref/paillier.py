"""Paillier additively-homomorphic cryptosystem (host reference).

Backs PaillierPrecompiled's on-chain ciphertext addition. The reference
snapshot (v3.1.2) reserves the error-code band and gas opcode for Paillier
(bcos-executor/src/precompiled/common/Common.h:108 "PaillierPrecompiled
-51699 ~ -51600", PrecompiledGas.h:55 `PaillierAdd = 0x13`) but ships no
implementation file; the callable precompile exists in the 2.x line. This
module provides the full scheme so the chain surface is complete and
testable end-to-end: keygen, encrypt, decrypt, and the homomorphic add the
precompile exposes.

Scheme (standard Paillier with g = n + 1):
    n = p*q,  ciphertext  c = (1 + m*n) * r^n  mod n^2
    Enc(m1) * Enc(m2) mod n^2  =  Enc(m1 + m2 mod n)

Ciphertext wire format (hex string on the ABI surface):
    2 bytes  key bit-length K, big-endian (must be a multiple of 8)
    K/8      n, big-endian
    K/4      c, big-endian  (one element of Z_{n^2})

The format is self-describing so `paillierAdd` can validate that both
operands were produced under the same public key — adding ciphertexts from
different keys is meaningless and is rejected, mapped into the reserved
error band rather than raised.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from math import gcd


@dataclass(frozen=True)
class PaillierPublicKey:
    n: int
    bits: int  # modulus bit-length as serialized (multiple of 8)

    @property
    def n_sq(self) -> int:
        return self.n * self.n


@dataclass(frozen=True)
class PaillierPrivateKey:
    pub: PaillierPublicKey
    lam: int  # lcm(p-1, q-1)
    mu: int  # (L(g^lam mod n^2))^-1 mod n


def _is_probable_prime(n: int, rounds: int = 32) -> bool:
    if n < 2:
        return False
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _random_prime(bits: int) -> int:
    while True:
        c = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _is_probable_prime(c):
            return c


def generate_keypair(bits: int = 1024) -> PaillierPrivateKey:
    """Key pair with an n of exactly ``bits`` bits (bits % 16 == 0)."""
    if bits % 16:
        raise ValueError("key size must be a multiple of 16 bits")
    while True:
        p = _random_prime(bits // 2)
        q = _random_prime(bits // 2)
        n = p * q
        if p != q and n.bit_length() == bits:
            break
    lam = (p - 1) * (q - 1) // gcd(p - 1, q - 1)
    pub = PaillierPublicKey(n=n, bits=bits)
    # g = n + 1: L(g^lam mod n^2) = lam mod n, so mu = lam^-1 mod n
    mu = pow(lam % n, -1, n)
    return PaillierPrivateKey(pub=pub, lam=lam, mu=mu)


def encrypt(pub: PaillierPublicKey, m: int) -> int:
    if not 0 <= m < pub.n:
        raise ValueError("plaintext out of range")
    while True:
        r = secrets.randbelow(pub.n - 1) + 1
        if gcd(r, pub.n) == 1:
            break
    n_sq = pub.n_sq
    return (1 + m * pub.n) % n_sq * pow(r, pub.n, n_sq) % n_sq


def decrypt(priv: PaillierPrivateKey, c: int) -> int:
    n, n_sq = priv.pub.n, priv.pub.n_sq
    if not 0 < c < n_sq:
        raise ValueError("ciphertext out of range")
    u = pow(c, priv.lam, n_sq)
    return (u - 1) // n % n * priv.mu % n


def serialize(pub: PaillierPublicKey, c: int) -> bytes:
    nb = pub.bits // 8
    return (
        pub.bits.to_bytes(2, "big")
        + pub.n.to_bytes(nb, "big")
        + c.to_bytes(2 * nb, "big")
    )


def deserialize(blob: bytes) -> tuple[PaillierPublicKey, int]:
    if len(blob) < 2:
        raise ValueError("ciphertext blob too short")
    bits = int.from_bytes(blob[:2], "big")
    if bits == 0 or bits % 8:
        raise ValueError("bad key bit-length")
    nb = bits // 8
    if len(blob) != 2 + 3 * nb:
        raise ValueError("ciphertext blob length mismatch")
    n = int.from_bytes(blob[2 : 2 + nb], "big")
    c = int.from_bytes(blob[2 + nb :], "big")
    if n.bit_length() != bits:
        raise ValueError("modulus bit-length mismatch")
    if not 0 < c < n * n:
        raise ValueError("ciphertext out of range")
    return PaillierPublicKey(n=n, bits=bits), c


def add_serialized(blob1: bytes, blob2: bytes) -> bytes:
    """Homomorphic add of two serialized ciphertexts (same public key)."""
    pub1, c1 = deserialize(blob1)
    pub2, c2 = deserialize(blob2)
    if pub1.n != pub2.n:
        raise ValueError("ciphertexts under different public keys")
    return serialize(pub1, c1 * c2 % pub1.n_sq)
