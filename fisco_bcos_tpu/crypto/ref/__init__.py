"""Pure-Python reference crypto — the CPU golden-vector source of truth.

The TPU batch kernels in ``fisco_bcos_tpu.ops`` must agree bit-exactly with these
(SURVEY.md §4: "golden crypto vectors — CPU reference vs TPU batch kernels must
agree bit-exactly"; any verify disagreement is consensus-fatal).
"""

from .keccak import keccak256
from .sha2 import sha256
from .sm3 import sm3
from .ecdsa import (
    SECP256K1,
    SM2_CURVE,
    Curve,
    ecdsa_sign,
    ecdsa_verify,
    ecdsa_recover,
    sm2_sign,
    sm2_verify,
    sm2_za,
    privkey_to_pubkey,
)

__all__ = [
    "keccak256",
    "sha256",
    "sm3",
    "SECP256K1",
    "SM2_CURVE",
    "Curve",
    "ecdsa_sign",
    "ecdsa_verify",
    "ecdsa_recover",
    "sm2_sign",
    "sm2_verify",
    "sm2_za",
    "privkey_to_pubkey",
]
