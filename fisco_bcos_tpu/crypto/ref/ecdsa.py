"""Pure-Python elliptic-curve reference: secp256k1 ECDSA (sign/verify/recover)
and SM2 (GB/T 32918) sign/verify.

Mirrors the reference semantics:
- secp256k1: 65-byte signature r‖s‖v with recovery id v
  (bcos-crypto signature/secp256k1/Secp256k1Crypto.cpp:106-108 accepts v∈{27,28}
  or {0,1}); recover returns the uncompressed public key; address =
  rightmost 160 bits of hash(pubkey) (CryptoSuite.h:56-59).
- SM2: 64-byte signature r‖s with the public key appended for "recover"
  (bcos-crypto signature/sm2/SM2Crypto.cpp:58-62, :81-91 — recover =
  parse-pubkey-then-verify). e = SM3(ZA ‖ M) with the default user id.

This is the golden-vector source for the TPU batch kernels in
fisco_bcos_tpu.ops.{secp256k1,sm2}.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

from .sm3 import sm3


@dataclass(frozen=True)
class Curve:
    name: str
    p: int
    a: int
    b: int
    gx: int
    gy: int
    n: int


SECP256K1 = Curve(
    name="secp256k1",
    p=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F,
    a=0,
    b=7,
    gx=0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    gy=0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
    n=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
)

SM2_CURVE = Curve(
    name="sm2p256v1",
    p=0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF00000000FFFFFFFFFFFFFFFF,
    a=0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF00000000FFFFFFFFFFFFFFFC,
    b=0x28E9FA9E9D9F5E344D5A9E4BCF6509A7F39789F515AB8F92DDBCBD414D940E93,
    gx=0x32C4AE2C1F1981195F9904466A39C9948FE30BBFF2660BE1715A4589334C74C7,
    gy=0xBC3736A2F4F6779C59BDCEE36B692153D0A9877CC62A474002DF32E52139F0A0,
    n=0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFF7203DF6B21C6052B53BBF40939D54123,
)

# Affine points are (x, y) int tuples; None is the point at infinity.


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def point_add(c: Curve, P, Q):
    if P is None:
        return Q
    if Q is None:
        return P
    x1, y1 = P
    x2, y2 = Q
    if x1 == x2:
        if (y1 + y2) % c.p == 0:
            return None
        lam = (3 * x1 * x1 + c.a) * _inv(2 * y1, c.p) % c.p
    else:
        lam = (y2 - y1) * _inv(x2 - x1, c.p) % c.p
    x3 = (lam * lam - x1 - x2) % c.p
    y3 = (lam * (x1 - x3) - y1) % c.p
    return (x3, y3)


def point_mul(c: Curve, k: int, P):
    k %= c.n
    R = None
    A = P
    while k:
        if k & 1:
            R = point_add(c, R, A)
        A = point_add(c, A, A)
        k >>= 1
    return R


def on_curve(c: Curve, P) -> bool:
    """On-curve check for CANONICAL affine coordinates: 0 <= x, y < p.

    Coordinates outside [0, p) are rejected rather than reduced — an
    attacker-chosen x+p encoding of a valid point must not verify on one
    implementation (this one reduces mod p) and fail on another (the native
    core and the device kernels range-check), or the chain forks on that tx."""
    if P is None:
        return True
    x, y = P
    if not (0 <= x < c.p and 0 <= y < c.p):
        return False
    return (y * y - (x * x * x + c.a * x + c.b)) % c.p == 0


def privkey_to_pubkey(c: Curve, d: int):
    """Returns affine (x, y)."""
    return point_mul(c, d, (c.gx, c.gy))


def _rfc6979_k(c: Curve, d: int, z: int, retry: int = 0) -> int:
    """Deterministic nonce (RFC 6979, HMAC-SHA256) — reproducible test vectors.

    ``retry`` perturbs the derivation (extra entropy octet) so r==0/s==0 retry
    loops get a fresh nonce for the SAME message."""
    holen = 32
    x = d.to_bytes(32, "big")
    h1 = (z % c.n).to_bytes(32, "big")
    if retry:
        h1 += retry.to_bytes(4, "big")
    v = b"\x01" * holen
    k = b"\x00" * holen
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < c.n:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def ecdsa_sign(msg_hash: bytes, d: int, c: Curve = SECP256K1):
    """Returns (r, s, v) with low-s normalization.

    v ∈ {0,1,2,3} is the recovery id (bit 1 set only in the ~2^-128 case
    rx ≥ n); practically always {0,1}, matching the reference's accepted
    encodings (Secp256k1Crypto.cpp:106-108 also accepts v+27)."""
    z = int.from_bytes(msg_hash, "big")
    for retry in range(64):
        k = _rfc6979_k(c, d, z, retry)
        R = point_mul(c, k, (c.gx, c.gy))
        assert R is not None
        rx, ry = R
        r = rx % c.n
        if r == 0:
            continue  # fresh k via retry counter; astronomically unlikely
        s = _inv(k, c.n) * (z + r * d) % c.n
        if s == 0:
            continue
        v = (ry & 1) | (2 if rx >= c.n else 0)
        if s > c.n // 2:
            s = c.n - s
            v ^= 1
        return (r, s, v)
    raise RuntimeError("ecdsa_sign: could not produce a signature")


def ecdsa_verify(msg_hash: bytes, r: int, s: int, pub, c: Curve = SECP256K1) -> bool:
    if not (1 <= r < c.n and 1 <= s < c.n):
        return False
    if pub is None or not on_curve(c, pub):
        return False
    z = int.from_bytes(msg_hash, "big")
    w = _inv(s, c.n)
    u1 = z * w % c.n
    u2 = r * w % c.n
    R = point_add(c, point_mul(c, u1, (c.gx, c.gy)), point_mul(c, u2, pub))
    if R is None:
        return False
    return R[0] % c.n == r


def ecdsa_recover(msg_hash: bytes, r: int, s: int, v: int, c: Curve = SECP256K1):
    """Recover the public key; v may be 0-3 or 27/28-style. Returns (x, y) or None."""
    if v >= 27:
        v -= 27
    if not (0 <= v <= 3 and 1 <= r < c.n and 1 <= s < c.n):
        return None
    x = r + (c.n if v & 2 else 0)
    if x >= c.p:
        return None
    y_sq = (pow(x, 3, c.p) + c.a * x + c.b) % c.p
    y = pow(y_sq, (c.p + 1) // 4, c.p)  # p ≡ 3 (mod 4) for both curves
    if y * y % c.p != y_sq:
        return None
    if (y & 1) != (v & 1):
        y = c.p - y
    z = int.from_bytes(msg_hash, "big")
    rinv = _inv(r, c.n)
    # Q = r^-1 (s·R − z·G)
    Q = point_add(
        c,
        point_mul(c, s * rinv % c.n, (x, y)),
        point_mul(c, (-z) * rinv % c.n, (c.gx, c.gy)),
    )
    if Q is None or not on_curve(c, Q):
        return None
    return Q


# ---------------------------------------------------------------------------
# SM2 (GB/T 32918.2-2016 digital signatures)
# ---------------------------------------------------------------------------

SM2_DEFAULT_ID = b"1234567812345678"


def sm2_za_bytes(
    pub_xy: bytes,
    user_id: bytes = SM2_DEFAULT_ID,
    c: Curve = SM2_CURVE,
    sm3_fn=sm3,
) -> bytes:
    """ZA = SM3(ENTL ‖ ID ‖ a ‖ b ‖ Gx ‖ Gy ‖ Px ‖ Py); ``pub_xy`` is the
    64-byte x‖y encoding; ``sm3_fn`` lets callers ride a faster hasher
    (the native core) without forking the layout."""
    entl = (len(user_id) * 8).to_bytes(2, "big")
    data = (
        entl
        + user_id
        + c.a.to_bytes(32, "big")
        + c.b.to_bytes(32, "big")
        + c.gx.to_bytes(32, "big")
        + c.gy.to_bytes(32, "big")
        + pub_xy
    )
    return sm3_fn(data)


def sm2_za(pub, user_id: bytes = SM2_DEFAULT_ID, c: Curve = SM2_CURVE) -> bytes:
    px, py = pub
    return sm2_za_bytes(
        px.to_bytes(32, "big") + py.to_bytes(32, "big"), user_id, c
    )


def sm2_e_bytes(
    pub_xy: bytes,
    msg_hash: bytes,
    user_id: bytes = SM2_DEFAULT_ID,
    sm3_fn=sm3,
) -> bytes:
    """e = SM3(ZA ‖ M) as 32 bytes; M is the 32-byte tx hash being signed."""
    return sm3_fn(sm2_za_bytes(pub_xy, user_id, sm3_fn=sm3_fn) + msg_hash)


def sm2_e(msg_hash: bytes, pub, user_id: bytes = SM2_DEFAULT_ID) -> int:
    px, py = pub
    return int.from_bytes(
        sm2_e_bytes(px.to_bytes(32, "big") + py.to_bytes(32, "big"), msg_hash, user_id),
        "big",
    )


def sm2_sign(msg_hash: bytes, d: int, user_id: bytes = SM2_DEFAULT_ID):
    c = SM2_CURVE
    pub = privkey_to_pubkey(c, d)
    e = sm2_e(msg_hash, pub, user_id)
    for retry in range(64):
        k = _rfc6979_k(c, d, e, retry)
        P1 = point_mul(c, k, (c.gx, c.gy))
        assert P1 is not None
        r = (e + P1[0]) % c.n
        if r == 0 or r + k == c.n:
            continue  # fresh k via retry counter
        s = _inv(1 + d, c.n) * (k - r * d) % c.n
        if s == 0:
            continue
        return (r, s)
    raise RuntimeError("sm2_sign: could not produce a signature")


def sm2_verify(msg_hash: bytes, r: int, s: int, pub, user_id: bytes = SM2_DEFAULT_ID) -> bool:
    c = SM2_CURVE
    if not (1 <= r < c.n and 1 <= s < c.n):
        return False
    if pub is None or not on_curve(c, pub):
        return False
    t = (r + s) % c.n
    if t == 0:
        return False
    e = sm2_e(msg_hash, pub, user_id)
    P1 = point_add(c, point_mul(c, s, (c.gx, c.gy)), point_mul(c, t, pub))
    if P1 is None:
        return False
    return (e + P1[0]) % c.n == r
