"""Curve25519 VRF (ECVRF-EDWARDS25519-SHA512-TAI, RFC 9381) — pure Python.

Reference role: the wedpr curve25519 VRF behind CryptoPrecompiled's
``curve25519VRFVerify`` (bcos-executor/src/precompiled/CryptoPrecompiled.cpp:117
→ ``wedpr_curve25519_vrf_verify_utf8`` / ``wedpr_curve25519_vrf_proof_to_hash``)
and the rPBFT VRF-based leader selection seam. wedpr-crypto implements the
pre-RFC draft of the same ECVRF construction over curve25519; this module
implements the published RFC 9381 ciphersuite 0x03 (TAI hash-to-curve,
SHA-512, 16-byte challenges) — same proof shape (gamma ‖ c ‖ s, 80 bytes),
same security contract, documented spec pin instead of an unversioned FFI.

Host-side only: VRF verification is a per-proposal singleton (one proof per
leader election round), not a batch plane — no device path is warranted.
"""

from __future__ import annotations

import hashlib

from .ed25519 import (
    BASE,
    IDENT,
    L,
    P,
    _add,
    _compress,
    _decompress,
    _eq_points,
    _mul,
)

SUITE = b"\x03"  # ECVRF-EDWARDS25519-SHA512-TAI
PROOF_LEN = 80  # gamma(32) ‖ c(16) ‖ s(32)
_CLEN = 16


def _neg(p):
    x, y, z, t = p
    return (P - x) % P, y, z, (P - t) % P


def _cofactor_clear(p):
    return _mul(8, p)


def _is_small_order(p) -> bool:
    return _eq_points(_cofactor_clear(p), IDENT)


def _hash_to_curve_tai(pub: bytes, alpha: bytes):
    """Try-and-increment encode_to_curve (RFC 9381 §5.4.1.1)."""
    for ctr in range(256):
        h = hashlib.sha512(
            SUITE + b"\x01" + pub + alpha + bytes([ctr]) + b"\x00"
        ).digest()[:32]
        pt = _decompress(h)
        if pt is not None:
            return _cofactor_clear(pt)  # never small-order after clearing
    return None  # 2^-256-class improbability; callers treat as invalid


def _challenge(points) -> int:
    """RFC 9381 §5.4.3: c = first 16 bytes of SHA-512 over the point list."""
    h = hashlib.sha512(
        SUITE + b"\x02" + b"".join(_compress(p) for p in points) + b"\x00"
    ).digest()[:_CLEN]
    return int.from_bytes(h, "big")


def is_valid_public_key(pub: bytes) -> bool:
    """wedpr_curve25519_vrf_is_valid_public_key: on-curve and not small-order."""
    pt = _decompress(pub)
    return pt is not None and not _is_small_order(pt)


def vrf_prove(secret: int, alpha: bytes) -> bytes:
    """Proof pi = gamma ‖ c ‖ s for scalar secret key x (0 < x < L).

    Takes the raw scalar (not an RFC 8032 seed): VRF keys here are standalone
    scalars exactly like wedpr's curve25519 VRF keypairs.
    """
    x = secret % L
    if x == 0:
        raise ValueError("vrf secret must be nonzero mod L")
    pub_pt = _mul(x, BASE)
    pub = _compress(pub_pt)
    h_pt = _hash_to_curve_tai(pub, alpha)
    if h_pt is None:
        raise ValueError("hash_to_curve failed")
    gamma = _mul(x, h_pt)
    # deterministic nonce (RFC 9381 §5.4.2.2 shape, keyed by the raw scalar)
    k = (
        int.from_bytes(
            hashlib.sha512(
                x.to_bytes(32, "little") + _compress(h_pt)
            ).digest(),
            "little",
        )
        % L
    )
    c = _challenge([pub_pt, h_pt, gamma, _mul(k, BASE), _mul(k, h_pt)])
    s = (k + c * x) % L
    return (
        _compress(gamma)
        + c.to_bytes(_CLEN, "big")
        + s.to_bytes(32, "little")
    )


def vrf_verify(pub: bytes, alpha: bytes, pi: bytes) -> bool:
    """RFC 9381 §5.3 verify: U = s*B - c*Y, V = s*H - c*Gamma, c' == c."""
    if len(pi) != PROOF_LEN or len(pub) != 32:
        return False
    y_pt = _decompress(pub)
    if y_pt is None or _is_small_order(y_pt):
        return False
    gamma = _decompress(pi[:32])
    if gamma is None:
        return False
    c = int.from_bytes(pi[32 : 32 + _CLEN], "big")
    s = int.from_bytes(pi[32 + _CLEN :], "little")
    if s >= L:
        return False
    h_pt = _hash_to_curve_tai(pub, alpha)
    if h_pt is None:
        return False
    u = _add(_mul(s, BASE), _mul(c, _neg(y_pt)))
    v = _add(_mul(s, h_pt), _mul(c, _neg(gamma)))
    return _challenge([y_pt, h_pt, gamma, u, v]) == c


def vrf_proof_to_hash(pi: bytes) -> bytes | None:
    """beta (32 bytes) from a syntactically valid proof (RFC 9381 §5.2 shape,
    truncated to the 32-byte HashType the precompile returns as uint256)."""
    if len(pi) != PROOF_LEN:
        return None
    gamma = _decompress(pi[:32])
    if gamma is None:
        return None
    return hashlib.sha512(
        SUITE + b"\x03" + _compress(_cofactor_clear(gamma)) + b"\x00"
    ).digest()[:32]
