"""SM4 block cipher (GB/T 32907-2016) — pure-Python reference.

Reference role: bcos-crypto/encrypt/SM4Crypto.cpp (via wedpr FFI), consumed
by bcos-security's DataEncryption for national-secret deployments.  The
S-box and system parameters FK/CK are the published standard constants.
32-round unbalanced Feistel over 128-bit blocks; CBC + PKCS7 helpers at the
bottom match the reference's cipher mode.
"""

from __future__ import annotations

_SBOX = bytes.fromhex(
    "d690e9fecce13db716b614c228fb2c05"
    "2b679a762abe04c3aa44132649860699"
    "9c4250f491ef987a33540b43edcfac62"
    "e4b31ca9c908e89580df94fa758f3fa6"
    "4707a7fcf37317ba83593c19e6854fa8"
    "686b81b27164da8bf8eb0f4b70569d35"
    "1e240e5e6358d1a225227c3b01217887"
    "d40046579fd327524c3602e7a0c4c89e"
    "eabf8ad240c738b5a3f7f2cef96115a1"
    "e0ae5da49b341a55ad933230f58cb1e3"
    "1df6e22e8266ca60c02923ab0d534e6f"
    "d5db3745defd8e2f03ff6a726d6c5b51"
    "8d1baf92bbddbc7f11d95c411f105ad8"
    "0ac13188a5cd7bbd2d74d012b8e5b4b0"
    "8969974a0c96777e65b9f109c56ec684"
    "18f07dec3adc4d2079ee5f3ed7cb3948"
)
_FK = (0xA3B1BAC6, 0x56AA3350, 0x677D9197, 0xB27022DC)
_CK = tuple(
    sum(((4 * i + j) * 7 % 256) << (24 - 8 * j) for j in range(4)) for i in range(32)
)


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _tau(a: int) -> int:
    return int.from_bytes(bytes(_SBOX[b] for b in a.to_bytes(4, "big")), "big")


def _t(a: int) -> int:  # round transform
    b = _tau(a)
    return b ^ _rotl(b, 2) ^ _rotl(b, 10) ^ _rotl(b, 18) ^ _rotl(b, 24)


def _t_prime(a: int) -> int:  # key-schedule transform
    b = _tau(a)
    return b ^ _rotl(b, 13) ^ _rotl(b, 23)


def expand_key(key: bytes) -> list[int]:
    if len(key) != 16:
        raise ValueError("SM4 key must be 16 bytes")
    mk = [int.from_bytes(key[i : i + 4], "big") for i in range(0, 16, 4)]
    k = [mk[i] ^ _FK[i] for i in range(4)]
    rk = []
    for i in range(32):
        k.append(k[i] ^ _t_prime(k[i + 1] ^ k[i + 2] ^ k[i + 3] ^ _CK[i]))
        rk.append(k[-1])
    return rk


def _crypt_block(rk: list[int], block: bytes) -> bytes:
    x = [int.from_bytes(block[i : i + 4], "big") for i in range(0, 16, 4)]
    for i in range(32):
        x.append(x[i] ^ _t(x[i + 1] ^ x[i + 2] ^ x[i + 3] ^ rk[i]))
    return b"".join(v.to_bytes(4, "big") for v in reversed(x[32:36]))


def encrypt_block(key: bytes, block: bytes) -> bytes:
    return _crypt_block(expand_key(key), block)


def decrypt_block(key: bytes, block: bytes) -> bytes:
    return _crypt_block(list(reversed(expand_key(key))), block)


# ---------------------------------------------------------------------------
# CBC mode + PKCS7 (the reference's SM4 CBC usage)
# ---------------------------------------------------------------------------


def _pad(data: bytes) -> bytes:
    n = 16 - len(data) % 16
    return data + bytes([n]) * n


def _unpad(data: bytes) -> bytes:
    if not data or len(data) % 16:
        raise ValueError("bad padded length")
    n = data[-1]
    if not 1 <= n <= 16 or data[-n:] != bytes([n]) * n:
        raise ValueError("bad padding")
    return data[:-n]


def cbc_encrypt(key: bytes, iv: bytes, plaintext: bytes) -> bytes:
    rk = expand_key(key)
    out, prev = [], iv
    data = _pad(plaintext)
    for i in range(0, len(data), 16):
        block = bytes(a ^ b for a, b in zip(data[i : i + 16], prev))
        prev = _crypt_block(rk, block)
        out.append(prev)
    return b"".join(out)


def cbc_decrypt(key: bytes, iv: bytes, ciphertext: bytes) -> bytes:
    rk = list(reversed(expand_key(key)))
    out, prev = [], iv
    for i in range(0, len(ciphertext), 16):
        block = ciphertext[i : i + 16]
        out.append(bytes(a ^ b for a, b in zip(_crypt_block(rk, block), prev)))
        prev = block
    return _unpad(b"".join(out))
