"""Linkable ring signatures (LSAG) over edwards25519.

Reference role: RingSigPrecompiled (0x5005,
bcos-executor/src/precompiled/extension/RingSigPrecompiled.cpp →
``RingSigApi::LinkableRingSig::ring_verify`` from group-signature-server).
The reference's FFI implements a linkable ring signature: any member of an
ad-hoc public-key ring can sign; the verifier learns only that SOME ring
member signed, and two signatures by the same key are linkable through the
key image. This module implements LSAG (Liu–Wei–Wong 2004, the scheme that
construction is based on) over edwards25519 with SHA-512.

Wire format (all little-endian 32-byte scalars, compressed points):
    signature = key_image(32) ‖ c0(32) ‖ s_0..s_{n-1} (32 each)
    ring      = concatenated compressed public keys (32 each)
"""

from __future__ import annotations

import hashlib
import secrets

from .ed25519 import (
    BASE,
    IDENT,
    L,
    _add,
    _compress,
    _decompress,
    _eq_points,
    _mul,
)


def _rand() -> int:
    return (secrets.randbits(255) % (L - 1)) + 1


def _hash_scalar(*parts: bytes) -> int:
    h = hashlib.sha512(b"fisco-tpu-lsag/")
    for p in parts:
        h.update(len(p).to_bytes(2, "little"))
        h.update(p)
    return int.from_bytes(h.digest(), "little") % L


def _hash_point(data: bytes):
    """Hash-to-point (try-and-increment, cofactor-cleared) for key images."""
    for ctr in range(256):
        cand = hashlib.sha512(
            b"fisco-tpu-lsag/point" + bytes([ctr]) + data
        ).digest()[:32]
        pt = _decompress(cand)
        if pt is not None:
            pt8 = _mul(8, pt)
            if not _eq_points(pt8, IDENT):
                return pt8
    raise ValueError("hash_to_point failed")  # 2^-256-class


def keypair(secret: int | None = None) -> tuple[int, bytes]:
    x = (secret or _rand()) % L
    return x, _compress(_mul(x, BASE))


def ring_sign(msg: bytes, ring: list[bytes], secret: int, index: int) -> bytes:
    """LSAG sign: `secret` is the private key of ring[index]."""
    n = len(ring)
    if not 0 <= index < n:
        raise ValueError("signer index out of ring")
    x = secret % L
    ring_blob = b"".join(ring)
    hp = _hash_point(ring[index])  # H(P_i): key-image base
    image = _mul(x, hp)
    image_b = _compress(image)

    s = [0] * n
    c = [0] * n
    a = _rand()
    c[(index + 1) % n] = _hash_scalar(
        ring_blob, image_b, msg,
        _compress(_mul(a, BASE)), _compress(_mul(a, hp)),
    )
    i = (index + 1) % n
    while i != index:
        s[i] = _rand()
        pk = _decompress(ring[i])
        if pk is None:
            raise ValueError("invalid ring member key")
        hp_i = _hash_point(ring[i])
        l_pt = _add(_mul(s[i], BASE), _mul(c[i], pk))
        r_pt = _add(_mul(s[i], hp_i), _mul(c[i], image))
        c[(i + 1) % n] = _hash_scalar(
            ring_blob, image_b, msg, _compress(l_pt), _compress(r_pt)
        )
        i = (i + 1) % n
    s[index] = (a - c[index] * x) % L
    return (
        image_b
        + c[0].to_bytes(32, "little")
        + b"".join(si.to_bytes(32, "little") for si in s)
    )


def ring_verify(msg: bytes, ring: list[bytes], sig: bytes) -> bool:
    n = len(ring)
    if n == 0 or len(sig) != 64 + 32 * n:
        return False
    image = _decompress(sig[:32])
    if image is None:
        return False
    # the image must lie in the PRIME-ORDER subgroup: a torsion-contaminated
    # image I' = x*H(P) + T (T of order 8) verifies whenever the signer
    # grinds the nonce until 8 | c, yielding a second unlinkable signature
    # from the same key — the classic CryptoNote key-image forgery. L*I == O
    # rejects every torsion component, not just pure small-order images.
    if not _eq_points(_mul(L, image), IDENT):
        return False
    if _eq_points(image, IDENT):
        return False
    ring_blob = b"".join(ring)
    image_b = sig[:32]
    c0 = int.from_bytes(sig[32:64], "little") % L
    c = c0
    for i in range(n):
        s_i = int.from_bytes(sig[64 + 32 * i : 96 + 32 * i], "little")
        if s_i >= L:
            return False
        pk = _decompress(ring[i])
        if pk is None:
            return False
        hp_i = _hash_point(ring[i])
        l_pt = _add(_mul(s_i, BASE), _mul(c, pk))
        r_pt = _add(_mul(s_i, hp_i), _mul(c, image))
        c = _hash_scalar(ring_blob, image_b, msg, _compress(l_pt), _compress(r_pt))
    return c == c0


def key_image(sig: bytes) -> bytes:
    """The linkability tag: equal images == same signer (across messages)."""
    return sig[:32]
