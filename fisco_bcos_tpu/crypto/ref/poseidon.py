"""Pure-Python Poseidon reference — the golden vectors for ops/poseidon.py.

Poseidon (2019/458) over the BN254 scalar field, the SNARK-friendly hash the
succinct state plane commits KeyPage state under (2407.03511: hash-
verification circuits are the first thing ZK blockchains optimize, so the
commitment hash must be circuit-cheap from day one).

Every parameter here is DERIVED, never transcribed (the BLS12-381 discipline
from ops/bls12_381.py): round constants come out of the Grain LFSR exactly as
the reference parameter generator specifies, and the MDS matrix is the
Cauchy construction 1/(x_i + y_j) — the jitted kernel re-asserts both over
plain ints at import, so a corrupted table cannot survive silently.

Instance: x^5 S-box, t = 3 (rate 2, capacity 1), 8 full + 57 partial rounds
— the standard 128-bit-security instance for this width/field.
"""

from __future__ import annotations

from functools import lru_cache

# BN254 (alt_bn128) scalar-field prime — the field Groth16/PLONK circuits
# natively compute in, hence the field the commitment hash must live in.
FR = 21888242871839275222246405745257275088548364400416034343698204186575808495617

T = 3  # state width
ALPHA = 5  # S-box exponent (gcd(5, FR - 1) == 1)
R_FULL = 8  # full rounds (split 4 + 4 around the partial run)
R_PARTIAL = 57  # partial rounds
N_ROUNDS = R_FULL + R_PARTIAL
RATE = T - 1  # sponge rate in field elements
CHUNK = 31  # bytes absorbed per field element (248 bits < 254-bit field)
BLOCK_BYTES = RATE * CHUNK  # 62-byte absorb granule
_FIELD_BITS = FR.bit_length()  # 254

# x^5 is a permutation of GF(FR) iff gcd(5, FR - 1) == 1
assert (FR - 1) % ALPHA != 0


def _grain_bits(field_bits: int, t: int, r_f: int, r_p: int):
    """Grain LFSR keystream per the Poseidon reference parameter generator.

    80-bit init = [field tag=1 (prime field), sbox tag=0 (x^alpha), n, t,
    R_F, R_P, 30 ones], each big-endian; feedback b_{i+80} = b_{i+62} ^
    b_{i+51} ^ b_{i+38} ^ b_{i+23} ^ b_{i+13} ^ b_i; first 160 bits
    discarded; then bits are drawn in pairs — a 1 emits the partner bit, a
    0 discards it (the generator's rejection step).
    """
    bits: list[int] = []

    def put(value: int, nbits: int) -> None:
        for i in range(nbits - 1, -1, -1):
            bits.append((value >> i) & 1)

    put(1, 2)  # GF(p)
    put(0, 4)  # x^alpha S-box
    put(field_bits, 12)
    put(t, 12)
    put(r_f, 10)
    put(r_p, 10)
    bits.extend([1] * 30)
    assert len(bits) == 80

    pos = 0

    def raw() -> int:
        nonlocal pos
        b = (
            bits[pos + 62]
            ^ bits[pos + 51]
            ^ bits[pos + 38]
            ^ bits[pos + 23]
            ^ bits[pos + 13]
            ^ bits[pos]
        )
        bits.append(b)
        pos += 1
        return b

    for _ in range(160):
        raw()
    while True:
        if raw():
            yield raw()
        else:
            raw()


def _sample_field(gen, count: int) -> list[int]:
    """Draw `count` field elements: 254 keystream bits big-endian, rejected
    and redrawn whenever the integer lands >= FR (no modular bias)."""
    out: list[int] = []
    while len(out) < count:
        v = 0
        for _ in range(_FIELD_BITS):
            v = (v << 1) | next(gen)
        if v < FR:
            out.append(v)
    return out


@lru_cache(maxsize=1)
def round_constants() -> tuple[tuple[int, ...], ...]:
    """[N_ROUNDS][T] Grain-derived round constants (ints < FR)."""
    gen = _grain_bits(_FIELD_BITS, T, R_FULL, R_PARTIAL)
    flat = _sample_field(gen, N_ROUNDS * T)
    return tuple(
        tuple(flat[r * T : (r + 1) * T]) for r in range(N_ROUNDS)
    )


@lru_cache(maxsize=1)
def mds_matrix() -> tuple[tuple[int, ...], ...]:
    """[T][T] Cauchy MDS: M[i][j] = 1/(x_i + y_j), x_i = i, y_j = T + j.

    x's and y's are pairwise distinct and x_i + y_j != 0, so the matrix is
    MDS over GF(FR); the invertibility of every entry IS the derivation —
    ops/poseidon.py asserts M[i][j] * (i + T + j) == 1 (mod FR)."""
    return tuple(
        tuple(pow(i + T + j, FR - 2, FR) for j in range(T)) for i in range(T)
    )


def _mix(state: list[int]) -> list[int]:
    m = mds_matrix()
    return [
        sum(m[i][j] * state[j] for j in range(T)) % FR for i in range(T)
    ]


def permutation(state) -> list[int]:
    """The Poseidon permutation over a T-element state of ints < FR."""
    if len(state) != T:
        raise ValueError("poseidon permutation wants a width-%d state" % T)
    state = [s % FR for s in state]
    rcs = round_constants()
    half = R_FULL // 2
    for rnd in range(N_ROUNDS):
        state = [(s + c) % FR for s, c in zip(state, rcs[rnd])]
        full = rnd < half or rnd >= half + R_PARTIAL
        if full:
            state = [pow(s, ALPHA, FR) for s in state]
        else:
            state[0] = pow(state[0], ALPHA, FR)
        state = _mix(state)
    return state


def pad_input(data: bytes) -> bytes:
    """Sponge padding: append 0x01, then zeros to a BLOCK_BYTES multiple.

    Injective over byte strings (the 0x01 marks the true end), and every
    31-byte chunk is < 2^248 < FR, so chunk -> field element is injective
    too."""
    padded = data + b"\x01"
    rem = len(padded) % BLOCK_BYTES
    if rem:
        padded += b"\x00" * (BLOCK_BYTES - rem)
    return padded


def absorb_elements(data: bytes) -> list[int]:
    """Padded input as the flat field-element sequence the sponge absorbs."""
    padded = pad_input(data)
    return [
        int.from_bytes(padded[i : i + CHUNK], "big")
        for i in range(0, len(padded), CHUNK)
    ]


def poseidon_hash(data: bytes) -> bytes:
    """Poseidon sponge hash: 32-byte big-endian digest (first state word)."""
    elems = absorb_elements(data)
    state = [0] * T
    for i in range(0, len(elems), RATE):
        for j in range(RATE):
            state[j] = (state[j] + elems[i + j]) % FR
        state = permutation(state)
    return state[0].to_bytes(32, "big")
