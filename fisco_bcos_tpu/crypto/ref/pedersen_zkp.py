"""Discrete-log zero-knowledge proofs over Pedersen commitments.

Reference role: the wedpr discrete-log ZKP suite behind ZkpPrecompiled
(bcos-crypto/bcos-crypto/zkp/discretezkp/DiscreteLogarithmZkp.cpp →
``wedpr_verify_*`` FFI; surfaced on-chain at 0x5100,
bcos-executor/src/precompiled/extension/ZkpPrecompiled.cpp). wedpr implements
these sigma protocols over curve25519; this module implements the same
relations over edwards25519 with an explicit SHA-512 Fiat–Shamir transcript
(domain-separated, all points+statement hashed), prover AND verifier — the
proofs are self-consistent and testable end-to-end rather than an opaque FFI.
Wire format: 32-byte compressed points, 32-byte little-endian scalars,
concatenated in the order documented per proof.

Relations (C = v*G + r*H is a Pedersen commitment, G = value base,
H = blinding base):
- knowledge:        know (v, r) for C
- equality:         know x with C1 = x*G1 and C2 = x*G2
- format:           know (v, r) with C1 = v*G + r*H and C2 = r*H2
- sum:              v1 + v2 = v3 given C1, C2, C3
- product:          v1 * v2 = v3 given C1, C2, C3
- either-equality:  value(C3) = value(C1) OR value(C3) = value(C2)
  (CDS OR-composition with split challenges)
"""

from __future__ import annotations

import hashlib
import secrets

from .ed25519 import (
    BASE,
    IDENT,
    L,
    P,
    _add,
    _compress,
    _decompress,
    _eq_points,
    _mul,
)


def _neg(p):
    x, y, z, t = p
    return (P - x) % P, y, z, (P - t) % P


def _sub(p, q):
    return _add(p, _neg(q))


def _scalar(data: bytes) -> int:
    return int.from_bytes(data, "little") % L


def _enc_scalar(s: int) -> bytes:
    return (s % L).to_bytes(32, "little")


def _rand_scalar() -> int:
    return (secrets.randbits(255) % (L - 1)) + 1


def _challenge(domain: bytes, *parts: bytes) -> int:
    h = hashlib.sha512(b"fisco-tpu-zkp/" + domain)
    for p in parts:
        h.update(len(p).to_bytes(2, "little"))
        h.update(p)
    return int.from_bytes(h.digest(), "little") % L


def pedersen_commit(v: int, r: int, g=None, h=None):
    g = g if g is not None else BASE
    h = h if h is not None else default_blinding_base()
    return _add(_mul(v % L, g), _mul(r % L, h))


_H_CACHE = None


def default_blinding_base():
    """H = hash-to-point of a fixed tag (nothing-up-my-sleeve: nobody knows
    log_G(H), which Pedersen hiding requires)."""
    global _H_CACHE
    # analysis: allow(atomicity, idempotent memo — the derivation is
    # deterministic, racing initializers compute the identical point)
    if _H_CACHE is None:
        ctr = 0
        while True:
            cand = hashlib.sha512(
                b"fisco-tpu-zkp/blinding-base" + bytes([ctr])
            ).digest()[:32]
            pt = _decompress(cand)
            if pt is not None:
                pt8 = _mul(8, pt)
                if not _eq_points(pt8, IDENT):
                    _H_CACHE = pt8
                    break
            ctr += 1
    return _H_CACHE


def aggregate_point(a: bytes, b: bytes) -> bytes | None:
    """Point addition on compressed encodings (wedpr aggregatePoint)."""
    pa, pb = _decompress(a), _decompress(b)
    if pa is None or pb is None:
        return None
    return _compress(_add(pa, pb))


def _dec(b: bytes):
    if len(b) != 32:
        return None
    return _decompress(b)


# -- knowledge proof: know (v, r) for C = vG + rH ---------------------------
# proof = T(32) ‖ z_v(32) ‖ z_r(32)


def prove_knowledge(v: int, r: int, g_b: bytes, h_b: bytes) -> tuple[bytes, bytes]:
    g, h = _dec(g_b), _dec(h_b)
    c_pt = _add(_mul(v % L, g), _mul(r % L, h))
    a, b = _rand_scalar(), _rand_scalar()
    t = _add(_mul(a, g), _mul(b, h))
    c = _challenge(b"knowledge", _compress(c_pt), _compress(t), g_b, h_b)
    return _compress(c_pt), (
        _compress(t) + _enc_scalar(a + c * v) + _enc_scalar(b + c * r)
    )


def verify_knowledge(c_b: bytes, proof: bytes, g_b: bytes, h_b: bytes) -> bool:
    if len(proof) != 96:
        return False
    c_pt, g, h, t = _dec(c_b), _dec(g_b), _dec(h_b), _dec(proof[:32])
    if None in (c_pt, g, h, t):
        return False
    z_v, z_r = _scalar(proof[32:64]), _scalar(proof[64:96])
    c = _challenge(b"knowledge", c_b, proof[:32], g_b, h_b)
    lhs = _add(_mul(z_v, g), _mul(z_r, h))
    rhs = _add(t, _mul(c, c_pt))
    return _eq_points(lhs, rhs)


# -- equality proof: know x with C1 = x*G1, C2 = x*G2 -----------------------
# proof = T1 ‖ T2 ‖ z


def prove_equality(x: int, g1_b: bytes, g2_b: bytes) -> tuple[bytes, bytes, bytes]:
    g1, g2 = _dec(g1_b), _dec(g2_b)
    c1, c2 = _mul(x % L, g1), _mul(x % L, g2)
    a = _rand_scalar()
    t1, t2 = _mul(a, g1), _mul(a, g2)
    c = _challenge(
        b"equality", _compress(c1), _compress(c2), _compress(t1), _compress(t2),
        g1_b, g2_b,
    )
    return (
        _compress(c1),
        _compress(c2),
        _compress(t1) + _compress(t2) + _enc_scalar(a + c * x),
    )


def verify_equality(
    c1_b: bytes, c2_b: bytes, proof: bytes, g1_b: bytes, g2_b: bytes
) -> bool:
    if len(proof) != 96:
        return False
    c1, c2, g1, g2 = _dec(c1_b), _dec(c2_b), _dec(g1_b), _dec(g2_b)
    t1, t2 = _dec(proof[:32]), _dec(proof[32:64])
    if None in (c1, c2, g1, g2, t1, t2):
        return False
    z = _scalar(proof[64:96])
    c = _challenge(b"equality", c1_b, c2_b, proof[:32], proof[32:64], g1_b, g2_b)
    return _eq_points(_mul(z, g1), _add(t1, _mul(c, c1))) and _eq_points(
        _mul(z, g2), _add(t2, _mul(c, c2))
    )


# -- format proof: C1 = v*G + r*H, C2 = r*H2 --------------------------------
# proof = T1 ‖ T2 ‖ z_v ‖ z_r


def prove_format(
    v: int, r: int, g_b: bytes, h_b: bytes, h2_b: bytes
) -> tuple[bytes, bytes, bytes]:
    g, h, h2 = _dec(g_b), _dec(h_b), _dec(h2_b)
    c1 = _add(_mul(v % L, g), _mul(r % L, h))
    c2 = _mul(r % L, h2)
    a, b = _rand_scalar(), _rand_scalar()
    t1 = _add(_mul(a, g), _mul(b, h))
    t2 = _mul(b, h2)
    c = _challenge(
        b"format", _compress(c1), _compress(c2), _compress(t1), _compress(t2),
        g_b, h_b, h2_b,
    )
    proof = (
        _compress(t1)
        + _compress(t2)
        + _enc_scalar(a + c * v)
        + _enc_scalar(b + c * r)
    )
    return _compress(c1), _compress(c2), proof


def verify_format(
    c1_b: bytes, c2_b: bytes, proof: bytes, g_b: bytes, h_b: bytes, h2_b: bytes
) -> bool:
    if len(proof) != 128:
        return False
    c1, c2, g, h, h2 = _dec(c1_b), _dec(c2_b), _dec(g_b), _dec(h_b), _dec(h2_b)
    t1, t2 = _dec(proof[:32]), _dec(proof[32:64])
    if None in (c1, c2, g, h, h2, t1, t2):
        return False
    z_v, z_r = _scalar(proof[64:96]), _scalar(proof[96:128])
    c = _challenge(
        b"format", c1_b, c2_b, proof[:32], proof[32:64], g_b, h_b, h2_b
    )
    ok1 = _eq_points(_add(_mul(z_v, g), _mul(z_r, h)), _add(t1, _mul(c, c1)))
    ok2 = _eq_points(_mul(z_r, h2), _add(t2, _mul(c, c2)))
    return ok1 and ok2


# -- sum proof: v1 + v2 = v3 ------------------------------------------------
# C3 - C1 - C2 = (r3-r1-r2)*H when the relation holds: one knowledge-of-dlog
# wrt H. proof = T ‖ z


def prove_sum(
    rs: tuple[int, int, int], commitments: tuple[bytes, bytes, bytes], h_b: bytes
) -> bytes:
    r1, r2, r3 = rs
    delta = (r3 - r1 - r2) % L
    h = _dec(h_b)
    a = _rand_scalar()
    t = _mul(a, h)
    c = _challenge(b"sum", *commitments, _compress(t), h_b)
    return _compress(t) + _enc_scalar(a + c * delta)


def verify_sum(
    c1_b: bytes, c2_b: bytes, c3_b: bytes, proof: bytes, g_b: bytes, h_b: bytes
) -> bool:
    if len(proof) != 64:
        return False
    c1, c2, c3, h, t = _dec(c1_b), _dec(c2_b), _dec(c3_b), _dec(h_b), _dec(proof[:32])
    if None in (c1, c2, c3, h, t):
        return False
    z = _scalar(proof[32:64])
    c = _challenge(b"sum", c1_b, c2_b, c3_b, proof[:32], h_b)
    d = _sub(_sub(c3, c1), c2)  # must be delta*H
    return _eq_points(_mul(z, h), _add(t, _mul(c, d)))


# -- product proof: v1 * v2 = v3 --------------------------------------------
# Prove C2 commits v2 under (G, H) AND C3 = v2*C1 + (r3 - v2*r1)*H — i.e.
# C3 commits the SAME v2 under base C1. proof = T1 ‖ T2 ‖ z_v ‖ z_r1 ‖ z_r2


def prove_product(
    vs: tuple[int, int, int],
    rs: tuple[int, int, int],
    commitments: tuple[bytes, bytes, bytes],
    g_b: bytes,
    h_b: bytes,
) -> bytes:
    v1, v2, _v3 = vs
    r1, r2, r3 = rs
    c1_b = commitments[0]
    g, h, c1 = _dec(g_b), _dec(h_b), _dec(c1_b)
    a, b1, b2 = _rand_scalar(), _rand_scalar(), _rand_scalar()
    t1 = _add(_mul(a, g), _mul(b1, h))
    t2 = _add(_mul(a, c1), _mul(b2, h))
    c = _challenge(
        b"product", *commitments, _compress(t1), _compress(t2), g_b, h_b
    )
    delta = (r3 - v2 * r1) % L
    return (
        _compress(t1)
        + _compress(t2)
        + _enc_scalar(a + c * v2)
        + _enc_scalar(b1 + c * r2)
        + _enc_scalar(b2 + c * delta)
    )


def verify_product(
    c1_b: bytes, c2_b: bytes, c3_b: bytes, proof: bytes, g_b: bytes, h_b: bytes
) -> bool:
    if len(proof) != 160:
        return False
    c1, c2, c3 = _dec(c1_b), _dec(c2_b), _dec(c3_b)
    g, h = _dec(g_b), _dec(h_b)
    t1, t2 = _dec(proof[:32]), _dec(proof[32:64])
    if None in (c1, c2, c3, g, h, t1, t2):
        return False
    z_v = _scalar(proof[64:96])
    z_r1 = _scalar(proof[96:128])
    z_r2 = _scalar(proof[128:160])
    c = _challenge(b"product", c1_b, c2_b, c3_b, proof[:32], proof[32:64], g_b, h_b)
    ok1 = _eq_points(_add(_mul(z_v, g), _mul(z_r1, h)), _add(t1, _mul(c, c2)))
    ok2 = _eq_points(_add(_mul(z_v, c1), _mul(z_r2, h)), _add(t2, _mul(c, c3)))
    return ok1 and ok2


# -- either-equality (OR) proof ---------------------------------------------
# value(C3) == value(C1)  OR  value(C3) == value(C2), without revealing
# which. Statement i: C3 - Ci = delta_i * H (same value -> blinding-only
# difference). CDS composition: simulate the false branch, split challenges
# c = c_1 + c_2. proof = T1 ‖ T2 ‖ c1 ‖ z1 ‖ z2  (c2 = c - c1 recomputed)


def prove_either_equality(
    which: int,
    delta: int,
    commitments: tuple[bytes, bytes, bytes],
    h_b: bytes,
) -> bytes:
    """`which` in (0, 1): the TRUE branch (C3 vs C1, or C3 vs C2); `delta`
    is its blinding difference r3 - r_i mod L."""
    c1, c2, c3 = (_dec(b) for b in commitments)
    h = _dec(h_b)
    d = [_sub(c3, c1), _sub(c3, c2)]
    # simulate the false branch
    c_false = _rand_scalar()
    z_false = _rand_scalar()
    t_false = _sub(_mul(z_false, h), _mul(c_false, d[1 - which]))
    a = _rand_scalar()
    t_true = _mul(a, h)
    ts = [None, None]
    ts[which], ts[1 - which] = t_true, t_false
    c_all = _challenge(
        b"either-equality", *commitments,
        _compress(ts[0]), _compress(ts[1]), h_b,
    )
    c_true = (c_all - c_false) % L
    z_true = (a + c_true * delta) % L
    cs = [None, None]
    zs = [None, None]
    cs[which], cs[1 - which] = c_true, c_false
    zs[which], zs[1 - which] = z_true, z_false
    return (
        _compress(ts[0])
        + _compress(ts[1])
        + _enc_scalar(cs[0])
        + _enc_scalar(zs[0])
        + _enc_scalar(zs[1])
    )


def verify_either_equality(
    c1_b: bytes, c2_b: bytes, c3_b: bytes, proof: bytes, g_b: bytes, h_b: bytes
) -> bool:
    if len(proof) != 160:
        return False
    c1, c2, c3, h = _dec(c1_b), _dec(c2_b), _dec(c3_b), _dec(h_b)
    t1, t2 = _dec(proof[:32]), _dec(proof[32:64])
    if None in (c1, c2, c3, h, t1, t2):
        return False
    c_1 = _scalar(proof[64:96])
    z1, z2 = _scalar(proof[96:128]), _scalar(proof[128:160])
    c_all = _challenge(
        b"either-equality", c1_b, c2_b, c3_b, proof[:32], proof[32:64], h_b
    )
    c_2 = (c_all - c_1) % L
    d1, d2 = _sub(c3, c1), _sub(c3, c2)
    ok1 = _eq_points(_mul(z1, h), _add(t1, _mul(c_1, d1)))
    ok2 = _eq_points(_mul(z2, h), _add(t2, _mul(c_2, d2)))
    return ok1 and ok2
