"""Fused tx-admission crypto step — the flagship device program.

One device program performs, for a whole block of transactions, what the
reference does one tx at a time on RPC/txpool threads
(``TxValidator::verify`` bcos-txpool/txpool/validator/TxValidator.cpp:27-69 →
``Transaction::verify()`` bcos-framework/protocol/Transaction.h:64-84):

    tx hash (keccak256)  →  ECDSA recover  →  sender = right160(keccak(pub))

The batch enters as pre-padded keccak block tensors plus signature limb
tensors, and leaves as (sender addresses, validity bitmap, recovered pubkeys).
Invalid lanes never raise — they lower a validity bit (consensus code must be
total). See also the #1 batch-verify hot loop in the reference,
bcos-txpool/sync/TransactionSync.cpp:521-553 (tbb::parallel_for over verify).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import keccak, secp256k1
from ..ops.address import sender_address_device
from ..ops.bigint import bytes_be_to_limbs, digest_words_le_to_limbs
from ..ops.hash_common import pad_keccak, pad_rows


def admission_core(blocks, nblocks, r, s, v):
    """The fused admission body, unjitted — shared verbatim by the single-chip
    jit (``admission_step``) and the sharded wrapper
    (parallel.sharding.sharded_admission), so the two paths cannot drift.

    blocks [B, M, 17, 2] + nblocks [B] are the pre-padded keccak form of each
    tx's signed payload; (r, s) [B, 16] limbs and v [B] int32 are the 65-byte
    signature split.

    Returns (addr [B, 20] uint32 bytes, ok bool[B], qx, qy, z [B, 16] limbs) —
    z is the tx hash as limbs, returned so callers reuse the digests instead
    of re-hashing the payloads in a second device pass.
    """
    words = keccak.keccak256_blocks(blocks, nblocks)
    z = digest_words_le_to_limbs(words)
    qx, qy, ok = secp256k1.recover_device(z, r, s, v)
    addr = sender_address_device(qx, qy)
    return addr, ok, qx, qy, z


admission_step = jax.jit(admission_core)


def pack_admission_device(addr, ok, qx, qy, z):
    """Pack the admission outputs into one uint8 tensor
    [B, 117] = addr(20) ‖ ok(1) ‖ pubkey(64) ‖ tx_hash(32): on a tunneled
    device each host fetch is a round trip, so the whole admission result
    crosses once instead of five times. Shared by the single-chip jit and
    the sharded wrapper (parallel.sharding.sharded_admission_packed)."""
    from ..ops.bigint import limbs_to_bytes_device

    return jnp.concatenate(
        [
            addr.astype(jnp.uint8),
            ok.astype(jnp.uint8)[:, None],
            limbs_to_bytes_device(qx).astype(jnp.uint8),
            limbs_to_bytes_device(qy).astype(jnp.uint8),
            limbs_to_bytes_device(z).astype(jnp.uint8),
        ],
        axis=1,
    )


def _admission_packed(blocks, nblocks, r, s, v):
    return pack_admission_device(*admission_core(blocks, nblocks, r, s, v))


admission_step_packed = jax.jit(_admission_packed)


def _admit_batch_native(payloads, sigs65):
    """Host-loop admission through the native C core (keccak → recover →
    address), bit-identical to the device program on valid lanes
    (tests/test_admission.py pins it). None when the native library is
    unavailable. ~0.3ms/sig — beats the DEVICE path outright when the jax
    backend is CPU XLA, and beats the tunnel round-trip for small batches."""
    from .. import native_bind

    if native_bind.load() is None:
        return None
    n = len(payloads)
    hashes = [native_bind.keccak256(p) for p in payloads]
    pubs_raw, oks = native_bind.secp256k1_recover_batch(
        b"".join(hashes),
        np.ascontiguousarray(sigs65[:, :32]).tobytes(),
        np.ascontiguousarray(sigs65[:, 32:64]).tobytes(),
        np.ascontiguousarray(sigs65[:, 64]).tobytes(),
        n,
    )
    pubs = np.frombuffer(pubs_raw, dtype=np.uint8).reshape(n, 64).copy()
    ok = np.asarray(oks, dtype=bool)
    pubs[~ok] = 0
    senders = np.zeros((n, 20), dtype=np.uint8)
    for i in range(n):
        if ok[i]:
            senders[i] = np.frombuffer(
                native_bind.keccak256(pubs[i].tobytes())[-20:], dtype=np.uint8
            )
    digests = np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(n, 32)
    return senders, ok, pubs, digests


# -- multi-device fan-out -----------------------------------------------------

_SHARD_CACHE: dict[int, object] = {}


def _shard_min() -> int:
    """Bucketed-batch floor for multi-device fan-out; merged plane batches
    at/above it shard over the local mesh (parallel/sharding.py). High by
    default: below ~thousands of lanes one chip is faster than paying the
    all_gather + an extra compiled program."""
    try:
        return int(os.environ.get("FISCO_DEVICE_SHARD_MIN", "4096"))
    except ValueError:
        return 4096


def _maybe_sharded_step(bb: int):
    """The cached sharded admission program when the bucketed batch `bb`
    clears the fan-out threshold on a multi-device mesh; None otherwise
    (single-chip jit). Mesh construction or compile failure falls back to
    the single-chip path — fan-out is an optimization, never a liveness
    dependency."""
    try:
        ndev = len(jax.devices())
        if ndev <= 1 or bb < max(_shard_min(), ndev) or bb % ndev:
            return None
        step = _SHARD_CACHE.get(ndev)
        if step is None:
            from ..parallel.sharding import make_mesh, sharded_admission_packed

            step = sharded_admission_packed(make_mesh(ndev))
            _SHARD_CACHE[ndev] = step
        return step
    except Exception:
        return None


def _admit_batch_device(
    payloads, sigs65, allow_shard: bool = False
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The fused device program (keccak → recover → address), one result
    transfer. `allow_shard=True` (plane dispatches only) fans the bucketed
    batch out over the local device mesh when it clears _shard_min."""
    from ..observability.device import device_span

    bsz = len(payloads)
    # pad_keccak buckets the batch dim itself (empty-message pad rows);
    # r/s/v follow the blocks tensor's bucket by construction
    blocks, nblocks = pad_keccak(list(payloads))
    bb = blocks.shape[0]
    step = _maybe_sharded_step(bb) if allow_shard else None
    op = "admission" if step is None else "admission_sharded"
    with device_span(op, bsz, shape_key=(bb, blocks.shape[1])):
        sigs65 = np.asarray(sigs65, dtype=np.uint8)
        r = pad_rows(bytes_be_to_limbs(sigs65[:, :32]), bb)
        s = pad_rows(bytes_be_to_limbs(sigs65[:, 32:64]), bb)
        v = pad_rows(sigs65[:, 64].astype(np.int32), bb)
        if step is None:
            step = admission_step_packed
        packed = np.asarray(step(blocks, nblocks, r, s, v))[:bsz]
        return (
            packed[:, :20],
            packed[:, 20] != 0,
            packed[:, 21:85],
            packed[:, 85:117],
        )


def _try_native(payloads, sigs65):
    """The native-host-loop leg when policy picks it; None to use device."""
    from ..observability.device import device_span
    from .suite import use_native_batch

    if os.environ.get("FISCO_FORCE_DEVICE_ADMISSION"):
        return None
    if not use_native_batch(len(payloads)):
        return None
    # native host loop — shape_key pinned so it never reads as
    # a compile; the op label keeps the dispatch split visible
    with device_span("admission_native", len(payloads), shape_key="native"):
        return _admit_batch_native(payloads, np.asarray(sigs65, dtype=np.uint8))


def _admit_direct(payloads, sigs65):
    """Pre-plane per-caller dispatch (the FISCO_DEVICE_PLANE=0 path):
    native-vs-device decided for THIS call alone — no coalescing, no
    fan-out, no breaker."""
    from .suite import _note_dispatch_path

    out = _try_native(payloads, sigs65)
    if out is not None:
        _note_dispatch_path("admission", "native")
        return out
    _note_dispatch_path("admission", "device")
    return _admit_batch_device(payloads, sigs65, allow_shard=False)


def _admit_merged(payloads, sigs65):
    """Plane-executor body: the same native-vs-device policy applied to the
    MERGED batch, with multi-device fan-out allowed and the device leg under
    the resilience breaker (host-loop fallback keeps admission serving when
    the device plane is degraded)."""
    from .suite import _device_or_host, _note_dispatch_path

    out = _try_native(payloads, sigs65)
    if out is not None:
        _note_dispatch_path("admission", "native")
        return out
    _note_dispatch_path("admission", "device")

    def _host(p, s):
        host_out = _admit_batch_native(p, np.asarray(s, dtype=np.uint8))
        if host_out is None:
            raise RuntimeError("native admission unavailable for host fallback")
        return host_out

    return _device_or_host(
        lambda p, s: _admit_batch_device(p, s, allow_shard=True),
        _host,
        payloads,
        sigs65,
    )


def _admission_plane_exec(reqs):
    """DevicePlane executor: merge every queued admission request (txpool
    RPC batches, consensus proposal re-verification, sync imports) into one
    policy decision + one device program, then slice results per request."""
    payloads: list[bytes] = []
    rows = []
    for r in reqs:
        payloads.extend(r.payload[0])
        rows.append(r.payload[1])
    sigs65 = np.concatenate(rows, axis=0)
    senders, ok, pubs, digests = _admit_merged(payloads, sigs65)
    senders, ok = np.asarray(senders), np.asarray(ok)
    pubs, digests = np.asarray(pubs), np.asarray(digests)
    out, lo = [], 0
    for r in reqs:
        hi = lo + r.n
        out.append((senders[lo:hi], ok[lo:hi], pubs[lo:hi], digests[lo:hi]))
        lo = hi
    return out


def admit_batch(
    payloads, sigs65
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host API: list[bytes] signed payloads + [B, 65] r‖s‖v signatures ->
    (senders [B, 20] uint8, ok bool[B], pubkeys [B, 64] uint8,
    tx hashes [B, 32] uint8). One device program, ONE result transfer —
    or the native host loop when that wins (small batch / CPU-only backend;
    crypto.suite.use_native_batch holds the policy).

    Routed through the shared DevicePlane: concurrent callers' batches
    coalesce into one program, shapes ride the bucket ladder, and oversized
    merged batches fan out over the device mesh. ``FISCO_DEVICE_PLANE=0``
    restores the per-caller direct dispatch exactly.
    FISCO_FORCE_DEVICE_ADMISSION=1 pins the device program (tests use it to
    cover the device path on CPU hosts)."""
    from ..device.plane import get_plane, plane_route, plane_wait

    bsz = len(payloads)
    if plane_route() and bsz:
        sigs_arr = np.asarray(sigs65, dtype=np.uint8)
        return plane_wait(get_plane().submit(
            "admission", (list(payloads), sigs_arr), bsz, _admission_plane_exec
        ))
    return _admit_direct(payloads, sigs65)


# -- progaudit shape spec: M=2 message-block dim (the short-payload bucket
# the flood pads to); both the raw core and the packed wrapper audit.
PROGSPEC = {
    "admission_core": {
        "bucket": 256,
        "inputs": lambda b: [
            ((b, 2, 17, 2), "uint32"), ((b,), "int32"),
            ((b, 16), "uint32"), ((b, 16), "uint32"), ((b,), "int32"),
        ],
    },
    "_admission_packed": {
        "bucket": 256,
        "inputs": lambda b: [
            ((b, 2, 17, 2), "uint32"), ((b,), "int32"),
            ((b, 16), "uint32"), ((b, 16), "uint32"), ((b,), "int32"),
        ],
    },
}
