"""Fused tx-admission crypto step — the flagship device program.

One device program performs, for a whole block of transactions, what the
reference does one tx at a time on RPC/txpool threads
(``TxValidator::verify`` bcos-txpool/txpool/validator/TxValidator.cpp:27-69 →
``Transaction::verify()`` bcos-framework/protocol/Transaction.h:64-84):

    tx hash (keccak256)  →  ECDSA recover  →  sender = right160(keccak(pub))

The batch enters as pre-padded keccak block tensors plus signature limb
tensors, and leaves as (sender addresses, validity bitmap, recovered pubkeys).
Invalid lanes never raise — they lower a validity bit (consensus code must be
total). See also the #1 batch-verify hot loop in the reference,
bcos-txpool/sync/TransactionSync.cpp:521-553 (tbb::parallel_for over verify).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import keccak, secp256k1
from ..ops.address import sender_address_device
from ..ops.bigint import bytes_be_to_limbs, digest_words_le_to_limbs
from ..ops.hash_common import pad_keccak, pad_rows


def admission_core(blocks, nblocks, r, s, v):
    """The fused admission body, unjitted — shared verbatim by the single-chip
    jit (``admission_step``) and the sharded wrapper
    (parallel.sharding.sharded_admission), so the two paths cannot drift.

    blocks [B, M, 17, 2] + nblocks [B] are the pre-padded keccak form of each
    tx's signed payload; (r, s) [B, 16] limbs and v [B] int32 are the 65-byte
    signature split.

    Returns (addr [B, 20] uint32 bytes, ok bool[B], qx, qy, z [B, 16] limbs) —
    z is the tx hash as limbs, returned so callers reuse the digests instead
    of re-hashing the payloads in a second device pass.
    """
    words = keccak.keccak256_blocks(blocks, nblocks)
    z = digest_words_le_to_limbs(words)
    qx, qy, ok = secp256k1.recover_device(z, r, s, v)
    addr = sender_address_device(qx, qy)
    return addr, ok, qx, qy, z


admission_step = jax.jit(admission_core)


def _admission_packed(blocks, nblocks, r, s, v):
    """admission_core with every output PACKED into one uint8 tensor
    [B, 117] = addr(20) ‖ ok(1) ‖ pubkey(64) ‖ tx_hash(32): on a tunneled
    device each host fetch is a round trip, so the whole admission result
    crosses once instead of five times."""
    from ..ops.bigint import limbs_to_bytes_device

    addr, ok, qx, qy, z = admission_core(blocks, nblocks, r, s, v)
    return jnp.concatenate(
        [
            addr.astype(jnp.uint8),
            ok.astype(jnp.uint8)[:, None],
            limbs_to_bytes_device(qx).astype(jnp.uint8),
            limbs_to_bytes_device(qy).astype(jnp.uint8),
            limbs_to_bytes_device(z).astype(jnp.uint8),
        ],
        axis=1,
    )


admission_step_packed = jax.jit(_admission_packed)


def _admit_batch_native(payloads, sigs65):
    """Host-loop admission through the native C core (keccak → recover →
    address), bit-identical to the device program on valid lanes
    (tests/test_admission.py pins it). None when the native library is
    unavailable. ~0.3ms/sig — beats the DEVICE path outright when the jax
    backend is CPU XLA, and beats the tunnel round-trip for small batches."""
    from .. import native_bind

    if native_bind.load() is None:
        return None
    n = len(payloads)
    hashes = [native_bind.keccak256(p) for p in payloads]
    pubs_raw, oks = native_bind.secp256k1_recover_batch(
        b"".join(hashes),
        np.ascontiguousarray(sigs65[:, :32]).tobytes(),
        np.ascontiguousarray(sigs65[:, 32:64]).tobytes(),
        np.ascontiguousarray(sigs65[:, 64]).tobytes(),
        n,
    )
    pubs = np.frombuffer(pubs_raw, dtype=np.uint8).reshape(n, 64).copy()
    ok = np.asarray(oks, dtype=bool)
    pubs[~ok] = 0
    senders = np.zeros((n, 20), dtype=np.uint8)
    for i in range(n):
        if ok[i]:
            senders[i] = np.frombuffer(
                native_bind.keccak256(pubs[i].tobytes())[-20:], dtype=np.uint8
            )
    digests = np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(n, 32)
    return senders, ok, pubs, digests


def admit_batch(
    payloads, sigs65
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host API: list[bytes] signed payloads + [B, 65] r‖s‖v signatures ->
    (senders [B, 20] uint8, ok bool[B], pubkeys [B, 64] uint8,
    tx hashes [B, 32] uint8). One device program, ONE result transfer —
    or the native host loop when that wins (small batch / CPU-only backend;
    crypto.suite.use_native_batch holds the policy).
    FISCO_FORCE_DEVICE_ADMISSION=1 pins the device program (tests use it to
    cover the device path on CPU hosts)."""
    from ..observability.device import device_span

    bsz = len(payloads)
    if not os.environ.get("FISCO_FORCE_DEVICE_ADMISSION"):
        from .suite import use_native_batch

        if use_native_batch(bsz):
            # native host loop — shape_key pinned so it never reads as
            # a compile; the op label keeps the dispatch split visible
            with device_span("admission_native", bsz, shape_key="native"):
                out = _admit_batch_native(
                    payloads, np.asarray(sigs65, dtype=np.uint8)
                )
            if out is not None:
                return out
    # pad_keccak buckets the batch dim itself (empty-message pad rows);
    # r/s/v follow the blocks tensor's bucket by construction
    blocks, nblocks = pad_keccak(list(payloads))
    bb = blocks.shape[0]
    with device_span("admission", bsz, shape_key=(bb, blocks.shape[1])):
        sigs65 = np.asarray(sigs65, dtype=np.uint8)
        r = pad_rows(bytes_be_to_limbs(sigs65[:, :32]), bb)
        s = pad_rows(bytes_be_to_limbs(sigs65[:, 32:64]), bb)
        v = pad_rows(sigs65[:, 64].astype(np.int32), bb)
        packed = np.asarray(admission_step_packed(blocks, nblocks, r, s, v))[:bsz]
        return (
            packed[:, :20],
            packed[:, 20] != 0,
            packed[:, 21:85],
            packed[:, 85:117],
        )
