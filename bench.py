#!/usr/bin/env python
"""North-star benchmark: batch secp256k1 admission on a 10k-tx block.

Measures the fused device program (keccak256 tx hash → ECDSA recover → sender
address) — the TPU replacement for the reference's per-tx CPU path
(``Transaction::verify()`` bcos-framework/protocol/Transaction.h:64-84 via
wedpr FFI, parallelized with tbb in bcos-txpool/sync/TransactionSync.cpp:521).

Baseline: the same 10k verifies on CPU via OpenSSL ECDSA (the `cryptography`
package), single-threaded and scaled by the host's core count — an optimistic
stand-in for the reference's tbb::parallel_for CryptoSuite loop (the reference
publishes no absolute crypto numbers; BASELINE.md documents this).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BLOCK_TXS = 10_000  # the BASELINE.json "10k-tx block" config
UNIQUE = 64


def _vectors():
    from fisco_bcos_tpu.crypto.ref import ecdsa as ref
    from fisco_bcos_tpu.crypto.ref.keccak import keccak256

    payloads, sigs, digests, pubs = [], [], [], []
    for i in range(UNIQUE):
        payload = b"bench parallel-transfer tx %06d" % i + b"\xab" * 64
        d = 0xBEEF + 104729 * i
        h = keccak256(payload)
        r, s, v = ref.ecdsa_sign(h, d)
        payloads.append(payload)
        digests.append(h)
        sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([v]))
        pubs.append(ref.privkey_to_pubkey(ref.SECP256K1, d))
    reps = -(-BLOCK_TXS // UNIQUE)
    payloads = (payloads * reps)[:BLOCK_TXS]
    sigs = np.frombuffer(b"".join(sigs * reps), dtype=np.uint8).reshape(-1, 65)[
        :BLOCK_TXS
    ]
    return payloads, sigs, digests, pubs


def _cpu_baseline_tps(digests, sigs_int, pubs) -> float:
    """OpenSSL (cryptography pkg) single-thread verify TPS × core count."""
    try:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec, utils
    except ImportError:
        return 15_000.0 * (os.cpu_count() or 1)  # typical libsecp256k1-class figure
    keys = [
        ec.EllipticCurvePublicNumbers(x, y, ec.SECP256K1()).public_key()
        for x, y in pubs
    ]
    ders = [
        utils.encode_dss_signature(r, s) for (r, s, _v) in sigs_int
    ]
    prehash = ec.ECDSA(utils.Prehashed(hashes.SHA256()))
    n_iter = 1000
    t0 = time.perf_counter()
    for i in range(n_iter):
        j = i % UNIQUE
        keys[j].verify(ders[j], digests[j], prehash)
    dt = time.perf_counter() - t0
    return n_iter / dt * (os.cpu_count() or 1)


def main() -> None:
    payloads, sigs, digests, pubs = _vectors()
    from fisco_bcos_tpu.crypto.admission import admit_batch
    from fisco_bcos_tpu.crypto.ref import ecdsa as ref

    # correctness gate: device admission must match the CPU reference exactly
    addr, ok, _ = admit_batch(payloads[:UNIQUE], sigs[:UNIQUE])  # also warms jit
    assert bool(ok.all()), "device admission rejected valid signatures"
    from fisco_bcos_tpu.crypto.ref.keccak import keccak256

    for j in (0, UNIQUE - 1):
        x, y = pubs[j]
        expect = keccak256(x.to_bytes(32, "big") + y.to_bytes(32, "big"))[12:]
        assert bytes(addr[j]) == expect, "sender address mismatch vs CPU reference"

    admit_batch(payloads, sigs)  # warm the full-block shape
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        _, ok, _ = admit_batch(payloads, sigs)
        times.append(time.perf_counter() - t0)
    assert bool(ok.all())
    tps = BLOCK_TXS / min(times)

    sigs_int = [
        (
            int.from_bytes(bytes(s[:32]), "big"),
            int.from_bytes(bytes(s[32:64]), "big"),
            int(s[64]),
        )
        for s in sigs[:UNIQUE]
    ]
    cpu_tps = _cpu_baseline_tps(digests, sigs_int, pubs)

    print(
        json.dumps(
            {
                "metric": "secp256k1_admission_verifies_per_s_10k_block",
                "value": round(tps, 1),
                "unit": "tx/s",
                "vs_baseline": round(tps / cpu_tps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
