#!/usr/bin/env python
"""North-star benchmark: batch secp256k1 admission on a 10k-tx block.

Times the fused device program (keccak256 tx hash → ECDSA recover → sender
address) — the TPU replacement for the reference's per-tx CPU path
(``Transaction::verify()`` bcos-framework/protocol/Transaction.h:64-84 via
wedpr FFI, parallelized with tbb in bcos-txpool/sync/TransactionSync.cpp:521).
Input tensors are pre-padded once (a node pads incrementally at submit time);
the timed region is the device program via block_until_ready.

Baseline: the same verifies on CPU via OpenSSL ECDSA (the `cryptography`
package), single-threaded and scaled by the host's core count — an optimistic
stand-in for the reference's tbb::parallel_for CryptoSuite loop (the reference
publishes no absolute crypto numbers; BASELINE.md documents this).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BLOCK_TXS = 10_000  # the BASELINE.json "10k-tx block" config
UNIQUE = 64


def _cpu_baseline_tps(digests, sigs65, pubs) -> float:
    """OpenSSL (cryptography pkg) single-thread verify TPS × core count."""
    ncpu = os.cpu_count() or 1
    try:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec, utils
    except ImportError:
        return 15_000.0 * ncpu  # typical libsecp256k1-class figure
    keys = [
        ec.EllipticCurvePublicNumbers(x, y, ec.SECP256K1()).public_key()
        for x, y in pubs
    ]
    ders = [
        utils.encode_dss_signature(
            int.from_bytes(bytes(s[:32]), "big"),
            int.from_bytes(bytes(s[32:64]), "big"),
        )
        for s in sigs65[:UNIQUE]
    ]
    prehash = ec.ECDSA(utils.Prehashed(hashes.SHA256()))
    n_iter = 1000
    t0 = time.perf_counter()
    for i in range(n_iter):
        j = i % UNIQUE
        keys[j].verify(ders[j], digests[j], prehash)
    dt = time.perf_counter() - t0
    return n_iter / dt * ncpu


def main() -> None:
    from fisco_bcos_tpu.crypto.admission import admission_step
    from fisco_bcos_tpu.crypto.ref.keccak import keccak256
    from fisco_bcos_tpu.crypto.testvec import admission_tensors, signed_payload_vectors
    from fisco_bcos_tpu.ops.hash_common import bucket_batch, pad_rows

    payloads, sigs, digests, pubs = signed_payload_vectors(
        BLOCK_TXS,
        unique=UNIQUE,
        payload_fn=lambda i: b"bench parallel-transfer tx %06d" % i + b"\xab" * 64,
        secret_fn=lambda i: 0xBEEF + 104729 * i,
    )
    blocks, nblocks, r, s, v = admission_tensors(payloads, sigs)
    bb = bucket_batch(BLOCK_TXS)
    args = tuple(pad_rows(a, bb) for a in (blocks, nblocks, r, s, v))

    # correctness gate + jit warmup: device must match the CPU reference
    addr, ok, *_rest = admission_step(*args)
    addr, ok = np.asarray(addr), np.asarray(ok)
    assert bool(ok[:BLOCK_TXS].all()), "device admission rejected valid signatures"
    for j in (0, UNIQUE - 1):
        x, y = pubs[j]
        expect = keccak256(x.to_bytes(32, "big") + y.to_bytes(32, "big"))[12:]
        assert bytes(addr[j].astype(np.uint8)) == expect, "sender address mismatch"

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = admission_step(*args)
        out[1].block_until_ready()
        times.append(time.perf_counter() - t0)
    tps = BLOCK_TXS / min(times)

    cpu_tps = _cpu_baseline_tps(digests, sigs, pubs)
    print(
        json.dumps(
            {
                "metric": "secp256k1_admission_verifies_per_s_10k_block",
                "value": round(tps, 1),
                "unit": "tx/s",
                "vs_baseline": round(tps / cpu_tps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
