#!/usr/bin/env python
"""Benchmarks against BASELINE.md configs — one JSON line per metric,
headline (north-star) first.

Metrics:
1. secp256k1_admission_verifies_per_s_10k_block (headline): the fused
   keccak->recover->address device program over a 10k-tx block vs an
   OpenSSL-per-core CPU baseline (Transaction::verify(),
   bcos-txpool/sync/TransactionSync.cpp:521 hot loop).
2. block_verify_latency_ms_10k: wall latency of that same device program —
   the "block-verify latency" half of the north-star metric.
3. sm2_batch_verify_per_s_10k: national-crypto batch verify
   (SM2Crypto.cpp:29-91) vs per-core CPU SM2.
4. merkle_root_10k_leaves_ms: device wide-merkle over 10k keccak leaves
   (benchmark/merkleBench.cpp:36-67) vs a native-C sequential merkle/core.
5. e2e_flood_tps: FISCO_BENCH_FLOOD (default 3k) duplicated parallel-transfer txs
   (DupTestTxJsonRpcImpl_2_0.h flood) through a live FOUR-NODE PBFT chain
   (BASELINE config #4) — admission, payload gossip, three-phase consensus,
   replica re-execution x4, 2PC commit x4; vs_baseline is the reference's
   published 10k TPS claim (README.md:10).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# persistent XLA compile cache: the EC/keccak programs are multi-minute
# compiles; cache them across bench runs (shared with tests + dryrun)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)


def _init_jax() -> None:
    """jax import + cache config — called by the --only children (and the
    bench functions' own imports), NOT by the orchestrating parent, which
    never touches a device."""
    _child = os.environ.get("FISCO_BENCH_CHILD_NAME") or ""
    if os.environ.get("FISCO_BENCH_CPU_FALLBACK") and (
        _child in ("admission", "sm2") or _child.startswith("scenario")
    ):
        # tunnel down: the EC children's numbers are already
        # degraded-and-labeled, so trade runtime for compile time the way
        # tests/conftest.py does — at full LLVM opt a single EC program
        # costs 200+s on this 1-core host and the child's budget slice dies
        # inside the compiler. Merkle/flood keep full opt (their programs
        # compile fast enough and their values are the artifact headline).
        # XLA_FLAGS is read at first backend init, which hasn't happened yet.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_backend_optimization_level" not in flags:
            flags += (
                " --xla_backend_optimization_level=0"
                " --xla_llvm_disable_expensive_passes=true"
            )
            os.environ["XLA_FLAGS"] = flags.strip()

    import jax

    if os.environ.get("FISCO_BENCH_CPU_FALLBACK"):
        # the axon sitecustomize pins JAX_PLATFORMS, so override post-import
        jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


_CPU_FALLBACK_NOTE = (
    "TPU tunnel unreachable; measured on CPU XLA fallback (NOT a TPU number)"
)

BLOCK_TXS = 10_000
UNIQUE = 64
FLOOD_TXS = int(os.environ.get("FISCO_BENCH_FLOOD", "3000"))


_NATIVE_FALLBACK_NOTE = (
    "device kernel requires the TPU; measured the framework's ACTUAL "
    "CPU-host dispatch (native C batch engine) instead"
)


def _cpu_fallback() -> bool:
    return bool(os.environ.get("FISCO_BENCH_CPU_FALLBACK"))

# single source of truth for every metric this harness owes the artifact:
# (name, unit) — bench functions emit through these; _emit_missing emits
# degraded placeholders for whichever never landed
M_SECP = ("secp256k1_admission_verifies_per_s_10k_block", "tx/s")
M_LATENCY = ("block_verify_latency_ms_10k", "ms")
M_SM2 = ("sm2_batch_verify_per_s_10k", "sig/s")
M_MERKLE = ("merkle_root_10k_leaves_ms", "ms")
M_FLOOD = ("e2e_flood_tps", "tx/s")
# requests per merged device dispatch during the flood (1.0 = no coalescing
# won; baseline is the plane-less per-caller dispatch, i.e. exactly 1.0)
M_COALESCE = ("device_plane_coalesce_ratio", "reqs/dispatch")
# p95 inter-node spread of the corrected quorum edge across the measured
# flood's aligned rounds (fleet observatory; 0 with FISCO_FLEET_OBS=0)
M_ROUND_SKEW = ("fleet_round_skew_ms_p95", "ms")
# commit-path copy amplification over the measured flood (ISSUE 19 storage
# observatory): entries copied per durably-written row, mean across the
# measured blocks (0 and unmeasured with FISCO_STORAGE_OBS=0)
M_STORAGE_AMP = ("storage_copy_amplification", "copies/row")
# the --only storage child's durable-backend batch-write leg; the other
# five (backend, op) rows/s lines ride along under their dynamic names
M_STORAGE_ROWS = ("storage_sqlite_write_rows_per_s", "rows/s")
ALL_METRICS = [M_SECP, M_LATENCY, M_SM2, M_MERKLE, M_FLOOD, M_COALESCE,
               M_ROUND_SKEW, M_STORAGE_AMP, M_STORAGE_ROWS]


_EMITTED: set[str] = set()


def _emit(
    metric: str,
    value: float,
    unit: str,
    vs_baseline: float,
    error: str | None = None,
    measured: bool = True,
) -> None:
    # only MEASURED emissions get the fallback tag — a never-measured
    # placeholder claiming "measured on CPU XLA" would contradict itself
    if measured and os.environ.get("FISCO_BENCH_CPU_FALLBACK"):
        error = f"{_CPU_FALLBACK_NOTE}; {error}" if error else _CPU_FALLBACK_NOTE
    rec = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 2),
    }
    if error:
        rec["error"] = error[:400]
    _EMITTED.add(metric)
    print(json.dumps(rec), flush=True)


def _cpu_secp_baseline_tps(digests, sigs65, pubs) -> float:
    """OpenSSL (cryptography pkg) single-thread verify TPS x core count."""
    ncpu = os.cpu_count() or 1
    try:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec, utils
    except ImportError:
        return 15_000.0 * ncpu  # typical libsecp256k1-class figure
    keys = [
        ec.EllipticCurvePublicNumbers(x, y, ec.SECP256K1()).public_key()
        for x, y in pubs
    ]
    ders = [
        utils.encode_dss_signature(
            int.from_bytes(bytes(s[:32]), "big"),
            int.from_bytes(bytes(s[32:64]), "big"),
        )
        for s in sigs65[:UNIQUE]
    ]
    prehash = ec.ECDSA(utils.Prehashed(hashes.SHA256()))
    n_iter = 1000
    t0 = time.perf_counter()
    for i in range(n_iter):
        j = i % UNIQUE
        keys[j].verify(ders[j], digests[j], prehash)
    dt = time.perf_counter() - t0
    return n_iter / dt * ncpu


def bench_admission() -> None:
    from fisco_bcos_tpu.crypto.admission import _admit_batch_native, admission_step
    from fisco_bcos_tpu.crypto.ref.keccak import keccak256
    from fisco_bcos_tpu.crypto.testvec import admission_tensors, signed_payload_vectors
    from fisco_bcos_tpu.ops.hash_common import bucket_batch, pad_rows

    payloads, sigs, digests, pubs = signed_payload_vectors(
        BLOCK_TXS,
        unique=UNIQUE,
        payload_fn=lambda i: b"bench parallel-transfer tx %06d" % i + b"\xab" * 64,
        secret_fn=lambda i: 0xBEEF + 104729 * i,
    )
    err = None
    if _cpu_fallback():
        # no TPU: XLA's CPU emulation of 256-bit limb EC is NOT this
        # framework's CPU path (admit_batch routes CPU backends to the
        # native engine — crypto/suite.use_native_batch), so measure what a
        # user on this host actually gets, and say so
        out = _admit_batch_native(payloads, np.asarray(sigs, dtype=np.uint8))
        if out is None:
            note = "no TPU and no native library: nothing honest to measure"
            _emit(M_SECP[0], 0.0, M_SECP[1], 0.0, error=note, measured=False)
            _emit(M_LATENCY[0], 0.0, M_LATENCY[1], 0.0, error=note, measured=False)
            return
        err = _NATIVE_FALLBACK_NOTE
        senders, ok, _pubs, _digests = out
        if not bool(ok.all()):
            err += "; native admission rejected valid signatures"
        t0 = time.perf_counter()
        _admit_batch_native(payloads, np.asarray(sigs, dtype=np.uint8))
        best = time.perf_counter() - t0
    else:
        blocks, nblocks, r, s, v = admission_tensors(payloads, sigs)
        bb = bucket_batch(BLOCK_TXS)
        args = tuple(pad_rows(a, bb) for a in (blocks, nblocks, r, s, v))

        # correctness gate + jit warmup: device must match the CPU reference.
        # A mismatch degrades the metric (error field) instead of killing it.
        addr, ok, *_rest = admission_step(*args)
        addr, ok = np.asarray(addr), np.asarray(ok)
        if not bool(ok[:BLOCK_TXS].all()):
            err = "device admission rejected valid signatures"
        for j in (0, UNIQUE - 1):
            x, y = pubs[j]
            expect = keccak256(x.to_bytes(32, "big") + y.to_bytes(32, "big"))[12:]
            if bytes(addr[j].astype(np.uint8)) != expect:
                err = err or "sender address mismatch"

        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = admission_step(*args)
            out[1].block_until_ready()
            times.append(time.perf_counter() - t0)
        best = min(times)
    tps = BLOCK_TXS / best

    cpu_tps = _cpu_secp_baseline_tps(digests, sigs, pubs)
    _emit(M_SECP[0], tps, M_SECP[1], tps / cpu_tps, error=err)
    cpu_block_ms = BLOCK_TXS / cpu_tps * 1000.0
    _emit(
        M_LATENCY[0],
        best * 1000.0,
        M_LATENCY[1],
        cpu_block_ms / (best * 1000.0),
        error=err,
    )


def bench_sm2() -> None:
    import hashlib

    from fisco_bcos_tpu.crypto.ref import ecdsa as ref
    from fisco_bcos_tpu.ops.sm2 import verify_batch

    n = BLOCK_TXS
    msgs, sigs, pubs = [], [], []
    for i in range(UNIQUE):
        d = 0x1234 + 7919 * i
        h = hashlib.sha256(b"sm2 bench %04d" % i).digest()
        r, s = ref.sm2_sign(h, d)
        msgs.append(h)
        sigs.append((r, s))
        pubs.append(ref.privkey_to_pubkey(ref.SM2_CURVE, d))

    def rep(arr):
        return np.tile(arr, (n // UNIQUE + 1, 1))[:n]

    hz = rep(np.stack([np.frombuffer(h, np.uint8) for h in msgs]))
    r_b = rep(np.stack([np.frombuffer(r.to_bytes(32, "big"), np.uint8) for r, _ in sigs]))
    s_b = rep(np.stack([np.frombuffer(s.to_bytes(32, "big"), np.uint8) for _, s in sigs]))
    pub_b = rep(
        np.stack(
            [
                np.frombuffer(x.to_bytes(32, "big") + y.to_bytes(32, "big"), np.uint8)
                for x, y in pubs
            ]
        )
    )

    if _cpu_fallback():
        # no TPU: measure the framework's ACTUAL CPU dispatch — the native
        # C batch loop the SM2Crypto suite routes CPU backends to — not
        # XLA's emulated limb arithmetic (see bench_admission)
        from fisco_bcos_tpu import native_bind
        from fisco_bcos_tpu.crypto.suite import sm_suite

        if native_bind.load() is None:
            _emit(M_SM2[0], 0.0, M_SM2[1], 0.0, measured=False,
                  error="no TPU and no native library: nothing honest to measure")
            return
        # time the suite's REAL dispatch (SM2Crypto.batch_verify -> native
        # loop INCLUDING the per-item e = SM3(ZA||M) derivation + packing),
        # so the number is exactly what a CPU-host node pays per signature
        impl = sm_suite().signature_impl
        pub_rows = np.stack([
            np.frombuffer(
                pubs[i % UNIQUE][0].to_bytes(32, "big")
                + pubs[i % UNIQUE][1].to_bytes(32, "big"), np.uint8,
            )
            for i in range(n)
        ])
        sig_rows = np.concatenate([r_b, s_b, pub_rows], axis=1)  # r‖s‖pubkey
        oks = impl.batch_verify(hz, pub_rows, sig_rows)
        err = _NATIVE_FALLBACK_NOTE
        if not bool(np.asarray(oks).all()):
            err += "; native sm2 verify rejected valid sigs"
        t0 = time.perf_counter()
        impl.batch_verify(hz, pub_rows, sig_rows)
        tps = n / (time.perf_counter() - t0)
    else:
        ok = verify_batch(hz, r_b, s_b, pub_b)
        err = (
            None
            if bool(np.asarray(ok)[:n].all())
            else "sm2 device verify rejected valid sigs"
        )
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            ok = verify_batch(hz, r_b, s_b, pub_b)
            np.asarray(ok)
            times.append(time.perf_counter() - t0)
        tps = n / min(times)

    # CPU baseline: the NATIVE C single-item SM2 verify x cores — the
    # honest stand-in for the reference's wedpr-Rust/OpenSSL-tassl path
    # (SM2Crypto.cpp:29-91, fast_sm2.cpp), replacing the old pure-Python
    # baseline that inflated vs_baseline ~50x
    from fisco_bcos_tpu import native_bind

    pub_bytes = [
        x.to_bytes(32, "big") + y.to_bytes(32, "big") for x, y in pubs
    ]
    es = [
        ref.sm2_e_bytes(pub_bytes[j], msgs[j]) for j in range(UNIQUE)
    ]
    t0 = time.perf_counter()
    if native_bind.load() is not None:
        iters = 2000
        for i in range(iters):
            j = i % UNIQUE
            r, s = sigs[j]
            if not native_bind.sm2_verify(es[j], r, s, pub_bytes[j]):
                err = err or "native sm2 verify rejected its own signature"
    else:
        iters = 20  # degraded: pure-Python fallback baseline
        err = err or "native baseline unavailable; pure-Python CPU baseline"
        for i in range(iters):
            j = i % UNIQUE
            r, s = sigs[j]
            if not ref.sm2_verify(msgs[j], r, s, pubs[j]):
                err = "cpu reference sm2 verify rejected its own signature"
    cpu_tps = iters / (time.perf_counter() - t0) * (os.cpu_count() or 1)
    _emit(M_SM2[0], tps, M_SM2[1], tps / cpu_tps, error=err)


def bench_merkle() -> None:
    import jax.numpy as jnp

    from fisco_bcos_tpu import native_bind
    from fisco_bcos_tpu.crypto.ref.keccak import keccak256
    from fisco_bcos_tpu.ops.merkle import MerkleTree, merkle_root

    n = BLOCK_TXS
    leaves = np.frombuffer(
        b"".join(keccak256(b"%d" % i) for i in range(256)) * (n // 256 + 1),
        dtype=np.uint8,
    )[: n * 32].reshape(n, 32).copy()

    # leaves live on device: in the sealing path tx/receipt hashes come out
    # of the batch hash kernels, so the root computation starts device-side
    dev_leaves = jnp.asarray(leaves)
    root = merkle_root(dev_leaves, hasher="keccak256")  # warmup
    assert root == MerkleTree(leaves).root  # correctness anchor
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        root = merkle_root(dev_leaves, hasher="keccak256")
        times.append(time.perf_counter() - t0)
    dev_ms = min(times) * 1000.0

    # CPU baseline: native C keccak sequential width-16 merkle, x cores
    hash_fn = native_bind.keccak256 if native_bind.load() else keccak256
    t0 = time.perf_counter()
    level = [bytes(leaves[i]) for i in range(n)]
    while len(level) > 1:
        level = [
            hash_fn(b"".join(level[g : g + 16])) for g in range(0, len(level), 16)
        ]
    cpu_ms = (time.perf_counter() - t0) * 1000.0 / (os.cpu_count() or 1)
    _emit(M_MERKLE[0], dev_ms, M_MERKLE[1], cpu_ms / dev_ms)


def bench_flood() -> None:
    """Flood a FOUR-NODE PBFT chain (BASELINE config #4: "4-node Air chain,
    PBFT, txpool flooded with parallel-transfer txs") — all four engines in
    one process over the in-proc gateway (the reference's PBFTFixture
    pattern), so the measured TPS pays admission on the receiving node,
    payload gossip, the full three-phase consensus, REPLICA re-execution
    and verification on every node, and the 2PC commit x4.  A solo chain
    would overstate TPS by skipping consensus + replication entirely."""
    from fisco_bcos_tpu.codec.abi import ABICodec
    from fisco_bcos_tpu.crypto.suite import ecdsa_suite
    from fisco_bcos_tpu.executor.precompiled import DAG_TRANSFER_ADDRESS
    from fisco_bcos_tpu.front import InprocGateway
    from fisco_bcos_tpu.ledger import ConsensusNode, GenesisConfig
    from fisco_bcos_tpu.node import Node, NodeConfig
    from fisco_bcos_tpu.protocol.transaction import TransactionFactory

    suite = ecdsa_suite()
    codec = ABICodec(suite.hash)
    n = FLOOD_TXS
    block_cap = min(5000, max(1000, n))
    keypairs = [
        suite.signature_impl.generate_keypair(secret=0xF100D + i) for i in range(4)
    ]
    cons = [ConsensusNode(kp.pub, weight=1) for kp in keypairs]
    gw = InprocGateway(auto=True)
    nodes = []
    for kp in keypairs:
        cfg = NodeConfig(
            genesis=GenesisConfig(
                consensus_nodes=list(cons), tx_count_limit=block_cap
            )
        )
        node = Node(cfg, keypair=kp)
        gw.connect(node.front)
        nodes.append(node)
    # ISSUE 14: with >1 core the flood runs the OVERLAPPED pipeline —
    # consensus messages on each engine's worker, 2PCs on the commit
    # workers, lazy roots resolving at quorum time. On a 1-core host the
    # worker threads can only time-slice one core (measured ~20% pure
    # GIL/queue tax, nothing to overlap INTO), so the drive defaults to
    # inline there — same pipeline semantics (lazy roots, zero-copy,
    # prebuild), minus thread thrash. FISCO_BENCH_FLOOD_WORKERS=0|1
    # overrides the auto-detection either way.
    workers_default = "1" if (os.cpu_count() or 1) > 1 else "0"
    if os.environ.get("FISCO_BENCH_FLOOD_WORKERS", workers_default) != "0":
        for node in nodes:
            node.engine.start_worker()

    fac = TransactionFactory(suite)
    sender = suite.signature_impl.generate_keypair(secret=0xF200D)

    def make_txs(tag: str):
        return [
            fac.create_signed(
                sender,
                chain_id="chain0",
                group_id="group0",
                block_limit=500,
                nonce=f"flood-{tag}-{i}",
                to=DAG_TRANSFER_ADDRESS,
                input=codec.encode_call("userAdd(string,uint256)", f"u{tag}{i}", 1),
            )
            for i in range(n)
        ]

    def leader_for_next(height: int) -> "Node":
        idx = nodes[0].pbft_config.leader_index(height, 0)
        target = nodes[0].pbft_config.nodes[idx].node_id
        return next(nd for nd in nodes if nd.node_id == target)

    def optimistic_head() -> int:
        # the pipelined sealer chains on the engine's optimistic head
        # (commits still in flight on the worker) — the drive loop must
        # pick the next leader the same way or it would stall the overlap
        return max(nd.engine.consensus_head()[0] for nd in nodes)

    err = None
    t_child = time.monotonic()
    child_budget = _child_budget_s()
    if os.environ.get("FISCO_BENCH_TELEMETRY"):
        # ISSUE 13: compile-ledger hooks must be live BEFORE the warm
        # (compile) round so cold compiles are measured, not inferred
        from fisco_bcos_tpu.observability.device import install_observatory

        install_observatory()

    def flood_round(txs, deadline: float | None = None):
        nonlocal err
        entry = nodes[0]
        results = entry.txpool.submit_batch(txs)
        rejected = sum(1 for r in results if r.status != 0)
        if rejected:
            err = err or f"{rejected}/{len(txs)} txs rejected at admission"
        # gossip payloads so whichever node leads can fill its proposals
        entry.tx_sync.maintain()
        # progress-based stall detection: with the overlapped pipeline a
        # False seal_and_submit is NORMAL (proposal in flight, prebuild
        # tick) — only a wall of no committed-height progress is a stall
        last_height, last_progress = optimistic_head(), time.monotonic()
        while entry.txpool.pending_count() > 0:
            # wall-clock cap, not tx count: a too-slow chain must yield a
            # (degraded, honest) number, never a killed child with no line
            now = time.monotonic()
            if deadline is not None and now > deadline:
                err = err or "flood stopped at wall-clock deadline"
                break
            head = optimistic_head()
            if head != last_height:
                last_height, last_progress = head, now
            elif now - last_progress > 15.0:
                err = err or f"flood stalled at height {head}"
                break
            leader = leader_for_next(head + 1)
            if not leader.sealer.seal_and_submit():
                time.sleep(0.002)  # votes/2PCs drain on the workers
        # the TPS window closes when the pipelined 2PCs land, not when
        # the pool empties — drain every node's commit worker, then wait
        # for replica convergence. All tail waits respect the child
        # deadline's remaining headroom: a wedged commit worker must
        # yield a degraded metric line, never a budget-killed child.
        hard_stop = deadline + 8.0 if deadline is not None else None

        def tail_budget(cap: float) -> float:
            if hard_stop is None:
                return cap
            return max(0.5, min(cap, hard_stop - time.monotonic()))

        for nd in nodes:
            if not nd.scheduler.drain_commits(tail_budget(30.0)):
                err = err or "commit worker failed to drain"
        tip = nodes[0].block_number()
        t_conv = time.monotonic() + tail_budget(15.0)
        while (
            any(nd.block_number() < tip for nd in nodes)
            and time.monotonic() < t_conv
        ):
            time.sleep(0.002)

    # round 1 warms every device program on the block path (admission batch
    # shapes, tx/receipt merkle, state root) on ALL FOUR nodes — a
    # production node compiles once per shape for its whole lifetime, so
    # steady-state TPS is the meaningful number; round 2 is the measured
    # one. Client-side signing happens outside the timed window (the
    # reference's flood helper likewise pre-builds txs —
    # DuplicateTransactionFactory.cpp).
    # the warm (compile) round may take at most 65% of the child budget so a
    # measured window always remains
    warm_deadline = (
        t_child + 0.65 * child_budget if child_budget is not None else None
    )
    flood_round(make_txs("w"), deadline=warm_deadline)
    backlog = nodes[0].txpool.pending_count()
    if backlog:
        err = f"warm round left {backlog} txs pending"  # would inflate TPS
    heights = {nd.block_number() for nd in nodes}
    if len(heights) != 1:
        err = err or f"nodes diverged after warm round: heights {sorted(heights)}"
    measured_txs = make_txs("m")
    before = nodes[0].ledger.total_transaction_count()
    measure_deadline = (
        t_child + child_budget - 10 if child_budget is not None else None
    )
    # ISSUE 9: the 100 Hz sampling profiler rides the MEASURED round under
    # --telemetry, so the round artifact carries where the interpreter
    # actually spent the flood window; its duty cycle (sample cost /
    # wall) is the honest on/off overhead bound on this 1-core host
    prof = None
    warm_ledger = None
    alloc_window = None
    # measured-window boundary (EVERY round since ISSUE 14, not only under
    # --telemetry): drop the warm/compile round's tx index and stage
    # totals so the round artifact's per-stage vector covers ONLY the
    # measured flood — otherwise round-over-round check_perf diffs would
    # be dominated by cold-vs-warm compile variance.
    from fisco_bcos_tpu.observability import critical_path
    from fisco_bcos_tpu.observability.pipeline import PIPELINE
    from fisco_bcos_tpu.observability.storagelog import STORAGE

    critical_path.clear_indexes()
    PIPELINE.reset()
    # ISSUE 19: the storage observatory's codec/copy ledger likewise
    # covers ONLY the measured window (warm-round compile churn would
    # otherwise dominate the round-over-round codec-bytes diff)
    STORAGE.reset()
    prev_round_doc = _load_flood_artifact()
    if os.environ.get("FISCO_BENCH_TELEMETRY"):
        from fisco_bcos_tpu.observability.device import LEDGER
        from fisco_bcos_tpu.observability.profiler import SamplingProfiler

        # the warm round's compile ledger is kept for the device artifact
        # (it is where the cold compiles live by design), then reset so
        # the measured window's per-op phase vector is compile-clean
        warm_ledger = {
            "ledger": LEDGER.snapshot(),
            "op_phase_ms": LEDGER.phase_totals(),
        }
        LEDGER.reset()
        prof = SamplingProfiler(hz=100.0)
        prof.start()
        if STORAGE.enabled:
            # ISSUE 19: the tracemalloc window rides the profiler cadence
            # — same measured round, same on/off overhead accounting
            from fisco_bcos_tpu.observability.storagelog import (
                AllocationWindow,
            )

            alloc_window = AllocationWindow().start()
    t0 = time.perf_counter()
    flood_round(measured_txs, deadline=measure_deadline)
    dt = time.perf_counter() - t0
    if prof is not None:
        prof.stop()
    alloc_top = alloc_window.top(15) if alloc_window is not None else None
    committed = nodes[0].ledger.total_transaction_count() - before
    if committed < n:
        err = err or f"only {committed}/{n} txs committed"
    # every replica must hold the same chain the TPS number claims
    tips = {nd.block_number() for nd in nodes}
    roots = {
        nd.ledger.header_by_number(nd.block_number()).state_root for nd in nodes
    }
    if len(tips) != 1 or len(roots) != 1:
        err = err or "replicas diverged during measured round"
    tps = committed / dt
    # recompile counts ride along so the next BENCH round can attribute the
    # e2e gap: with the plane on, a ragged flood must stay within the bucket
    # ladder instead of compiling one program per batch size
    from fisco_bcos_tpu.device.plane import get_plane, plane_enabled
    from fisco_bcos_tpu.observability.device import compile_counts

    print(
        "# flood device compiles per op (distinct bucketed shapes): "
        + json.dumps(compile_counts()),
        flush=True,
    )
    _emit(M_FLOOD[0], tps, M_FLOOD[1], tps / 10_000.0, error=err)  # vs README.md:10
    if prof is not None:
        _dump_pipeline_artifact("flood", tps, prof, dt)
        _dump_device_artifact("flood", dt, warm_ledger)
    else:
        # ISSUE 14: the per-stage self-time flood artifact is written
        # EVERY round so check_perf can diff consecutive rounds even
        # when --telemetry is off (no profiler fold in this shape)
        _dump_flood_round_artifact(tps, dt)
    # ISSUE 16: the fleet observatory's per-phase round spans + quorum-edge
    # skew, written every round next to the pipeline artifact (noop and
    # placeholder-emitting when FISCO_FLEET_OBS=0)
    _dump_flood_rounds_artifact(nodes, dt)
    # ISSUE 19: the storage observatory's commit-path ledger — codec
    # bytes/block, copy-amplification, per-shard 2PC p95, top alloc sites
    # (noop and placeholder-emitting with FISCO_STORAGE_OBS=0)
    _dump_storage_artifact(dt, alloc_top)
    _gate_flood_round(prev_round_doc, tps)
    if plane_enabled():
        plane = get_plane()
        plane.drain(10.0)
        ratio = plane.coalesce_ratio()
        print(
            f"# device plane: {plane.stats()} wait_p99_ms="
            f"{plane.wait_p99_ms():.2f}",
            flush=True,
        )
        _emit(M_COALESCE[0], ratio, M_COALESCE[1], ratio, error=err)
    else:
        _emit(
            M_COALESCE[0], 1.0, M_COALESCE[1], 1.0,
            error="device plane disabled (FISCO_DEVICE_PLANE=0)",
        )


def bench_scenario(name: str) -> None:
    """--scenario child: run a named scenario-lab workload on a live chain
    and emit a per-group TPS/latency breakdown (fisco_bcos_tpu/scenario/).

    Two artifact surfaces: JSON metric lines (one per group, plus the
    isolation ratio when applicable) and the full runner document written
    next to the bench output as ``bench_scenario.<name>.json`` — the
    per-group breakdown, quota/demotion snapshot, health registry, fault
    counts and the determinism digest for the seed."""
    from fisco_bcos_tpu.scenario import (
        ScenarioRunner,
        run_big_committee_bench,
        run_byzantine_bench,
        run_isolation_bench,
        run_proof_storm_bench,
    )

    seed = int(os.environ.get("FISCO_SCENARIO_SEED", "0") or 0)
    scale = float(os.environ.get("FISCO_SCENARIO_SCALE", "1") or 1)
    budget = _child_budget_s()
    deadline = max(budget - 20, 30) if budget is not None else None
    if name == "big-committee":
        doc = run_big_committee_bench(seed=seed, scale=scale, deadline_s=deadline)
        err = doc.get("error")
        ratio = doc["qc_bytes_ratio_64_vs_4"]
        # acceptance: committed-QC bytes constant in committee size —
        # n=64 within 1.1x of n=4 (vs_baseline >= 1.0 passes)
        _emit(
            "scenario_big_committee_qc_bytes_ratio", ratio, "x-n4",
            (1.1 / ratio) if ratio > 0 else 0.0, error=err,
        )
        speedup = doc["aggregate_speedup_vs_sequential_n64"]
        # acceptance: one aggregate verification beats n=64 sequential
        # per-vote verifies
        _emit(
            "scenario_big_committee_agg_speedup_n64", speedup, "x-sequential",
            speedup / 1.0, error=err,
        )
        _emit(
            "scenario_big_committee_verify_ms_n64",
            doc["committees"]["64"]["verify_ms_p50"], "ms",
            1.0 if not err else 0.0, error=err,
        )
        print(
            f"# big-committee: qc_bytes n4={doc['committees']['4']['qc_bytes']} "
            f"n64={doc['committees']['64']['qc_bytes']} (ratio {ratio}x), "
            f"verify_ms ratio {doc['verify_ms_ratio_64_vs_4']}x, "
            f"agg speedup {speedup}x vs sequential, "
            f"ed25519 bytes {doc['ed25519']}, "
            f"chain={doc.get('chain', {})}",
            flush=True,
        )
        group_docs = {}
    elif name == "byzantine":
        doc = run_byzantine_bench(seed=seed, scale=scale, deadline_s=deadline)
        err = doc.get("error")
        ratio = doc["liveness_ratio"]
        # acceptance: honest commit throughput with one byzantine replica
        # running the full attack catalog holds >= 0.5x the clean flood
        # (vs_baseline >= 1.0 passes)
        _emit(
            "scenario_byzantine_liveness_ratio", ratio, "x-clean",
            ratio / 0.5, error=err,
        )
        detected = sum(1 for r in doc["attacks"] if r["detected"])
        _emit(
            "scenario_byzantine_attacks_detected", detected, "attack",
            1.0 if doc["all_detected"] else 0.0,
            error=err
            or (None if doc["all_detected"] else "undetected or unrun attacks"),
        )
        # safety is binary: both legs' auditor reports must be clean AND
        # the adversary must land in the penalty box
        safe = (
            doc["audit_clean"]["ok"]
            and doc["audit_byzantine"]["ok"]
            and doc["adversary_demoted"]
        )
        _emit(
            "scenario_byzantine_audit_ok", 1.0 if safe else 0.0, "bool",
            1.0 if safe else 0.0,
            error=err
            or (
                None
                if safe
                else "chain-safety audit violations or adversary not demoted"
            ),
        )
        print(
            f"# byzantine: clean {doc['clean_tps']} tx/s vs attacked "
            f"{doc['byzantine_tps']} tx/s (liveness {ratio}x), "
            f"{detected}/{len(doc['attacks'])} attacks detected, "
            f"demoted={doc['adversary_demoted']}, "
            f"evidence={doc['evidence_counts']}, audit ok={safe}",
            flush=True,
        )
        group_docs = {}
    elif name == "byzantine-wire":
        from fisco_bcos_tpu.scenario import run_wire_bench

        doc = run_wire_bench(seed=seed, scale=scale, deadline_s=deadline)
        err = doc.get("error")
        ratio = doc["liveness_ratio"]
        # acceptance: same 0.5x liveness floor as the in-proc catalog, but
        # measured over real TCP sockets (connect/flood/redial included)
        _emit(
            "scenario_byzantine_wire_liveness_ratio", ratio, "x-clean",
            ratio / 0.5, error=err,
        )
        detected = sum(1 for r in doc.get("attacks", ()) if r["detected"])
        _emit(
            "scenario_byzantine_wire_attacks_detected", detected, "attack",
            1.0 if doc["all_detected"] else 0.0,
            error=err
            or (None if doc["all_detected"] else "undetected or unrun attacks"),
        )
        # committee-wide demotion: every honest node confirmed the
        # offender via gossiped evidence, within this many settle rounds
        rounds = doc["convergence_rounds_max"]
        _emit(
            "scenario_byzantine_wire_convergence_rounds", rounds, "round",
            1.0 if doc["gossip_converged"] else 0.0,
            error=err
            or (None if doc["gossip_converged"] else "gossip never converged"),
        )
        safe = (
            doc.get("audit_clean", {}).get("ok", False)
            and doc.get("audit_byzantine", {}).get("ok", False)
            and doc["adversary_demoted"]
        )
        _emit(
            "scenario_byzantine_wire_audit_ok", 1.0 if safe else 0.0, "bool",
            1.0 if safe else 0.0,
            error=err
            or (
                None
                if safe
                else "chain-safety audit violations or adversary not demoted"
            ),
        )
        print(
            f"# byzantine-wire: clean {doc['clean_tps']} tx/s vs attacked "
            f"{doc['byzantine_tps']} tx/s (liveness {ratio}x), "
            f"{detected} attacks detected, gossip converged="
            f"{doc['gossip_converged']} (rounds<={rounds}), "
            f"demoted={doc['adversary_demoted']}, audit ok={safe}",
            flush=True,
        )
        group_docs = {}
    elif name == "proof-storm":
        doc = run_proof_storm_bench(seed=seed, scale=scale, deadline_s=deadline)
        err = doc.get("error")
        speedup = doc["speedup_vs_direct"]
        # acceptance: >= 50x proofs/sec over the direct per-request
        # Ledger.tx_proof rebuild at 10^5 queued clients
        _emit(
            "scenario_proof_storm_proofs_per_s", doc["proofs_per_s"], "proof/s",
            speedup / 50.0, error=err,
        )
        _emit(
            "scenario_proof_storm_cache_hit_ratio", doc["cache_hit_ratio"],
            "ratio", doc["cache_hit_ratio"] / 0.9, error=err,
        )
        # the write path must keep >= 0.7x its solo TPS under the storm
        ratio = doc["flood"]["ratio"]
        _emit(
            "scenario_proof_storm_flood_tps_ratio", ratio, "x-solo",
            ratio / 0.7, error=err,
        )
        # ISSUE 18 succinct lanes: state membership proofs/sec off the
        # StatePlane snapshot (zero tolerated verify failures) and the
        # headers/sec of ONE aggregate multi-pairing admission vs the old
        # per-header pairing loop (>= 1x acceptance: aggregation must not
        # cost more than the loop it replaces)
        state = doc.get("state_proofs") or {}
        if state.get("proofs_served"):
            _emit(
                "scenario_proof_storm_state_proofs_per_s",
                state["proofs_per_s"], "proof/s",
                0.0 if state["verify_failures"] else 1.0, error=err,
            )
        sync = doc.get("header_sync") or {}
        if sync.get("headers_per_s"):
            _emit(
                "scenario_proof_storm_sync_headers_per_s",
                sync["headers_per_s"], "header/s",
                sync["speedup_vs_per_header"], error=err,
            )
        print(
            f"# proof-storm: {doc['proofs_served']} proofs to "
            f"{doc['queued_clients']} queued clients, "
            f"p95={doc['proof_batch_latency_ms_p95']}ms/batch, "
            f"steady {doc['proofs_per_s_steady']}/s vs direct "
            f"{doc['direct_baseline_proofs_per_s']}/s (speedup {speedup}x), "
            f"verify_failures={doc['verify_failures']}, "
            f"state {state.get('proofs_per_s', 0)}/s over "
            f"{state.get('committed_keys', 0)} keys, header sync "
            f"{sync.get('headers_per_s', 0)}/s aggregate "
            f"({sync.get('speedup_vs_per_header', 0)}x vs per-header)",
            flush=True,
        )
        group_docs = {}
    elif name == "isolation":
        doc = run_isolation_bench(seed=seed, scale=scale, deadline_s=deadline)
        ratio = doc["victim_ratio"]
        err = doc.get("error") or doc["combined"].get("error")
        # acceptance: victim keeps >= 0.7x of its solo TPS while the abuser
        # floods — vs_baseline is measured/required so >= 1.0 passes
        _emit(
            "scenario_isolation_victim_tps_ratio", ratio, "x-solo",
            ratio / 0.7, error=err,
        )
        # only the ABUSER group's shed counts as proof: the victim's own
        # quota drops (or solo-leg residue) passing the gate would claim
        # isolation that never happened
        abuser = doc["abuser_group"]
        shed = sum(
            v
            for k, v in doc["abuse_shed_counters"].items()
            if f'group="{abuser}"' in k
        )
        _emit(
            "scenario_isolation_abuse_shed_txs", shed, "tx",
            1.0 if shed > 0 else 0.0,
            error=None if shed > 0 else "no abuser traffic shed at admission",
        )
        group_docs = {
            **doc["combined"]["groups"],
            "solo:" + doc["victim_group"]: doc["solo"]["groups"][
                doc["victim_group"]
            ],
        }
    else:
        doc = ScenarioRunner(
            name, seed=seed, scale=scale, deadline_s=deadline
        ).run()
        group_docs = doc["groups"]
    for g, gd in sorted(group_docs.items()):
        label = g.replace(":", "_")
        _emit(
            f"scenario_{name}_{label}_tps", gd["tps"], "tx/s", 0.0,
            error=doc.get("error"),
        )
        print(
            f"# scenario {name} group={g} submitted={gd['submitted']} "
            f"admitted={gd['admitted']} committed={gd['committed']} "
            f"rejected={gd['rejected']} p50={gd['latency_ms_p50']}ms "
            f"p95={gd['latency_ms_p95']}ms",
            flush=True,
        )
    base = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(base, f"bench_scenario.{name}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    print(
        f"# scenario artifact -> {path} (seed={seed}, digest="
        f"{doc.get('determinism_digest', doc.get('combined', {}).get('determinism_digest', ''))[:16]})",
        flush=True,
    )


def _flood_artifact_path() -> str:
    base = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(base, "bench_telemetry.flood.pipeline.json")


def _load_flood_artifact() -> dict | None:
    """Previous round's flood artifact (None on first round / bad file)."""
    try:
        with open(_flood_artifact_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _flood_round_doc(tag: str, tps: float, window_s: float) -> dict:
    """The round-artifact base document — everything check_perf diffs
    (flood TPS, per-stage self-time vector, /pipeline snapshot). Single-
    sourced so the --telemetry writer (which adds the profiler fold) and
    the every-round writer stay key-compatible across rounds."""
    from fisco_bcos_tpu.observability import critical_path
    from fisco_bcos_tpu.observability.pipeline import PIPELINE, pipeline_doc

    PIPELINE.sample_once()  # final watermark sweep before the snapshot
    agg = critical_path.aggregate_stage_self_ms()
    return {
        "tag": tag,
        "flood_tps": round(tps, 2),
        "window_s": round(window_s, 3),
        "stage_self_ms": {
            name: v["self_ms"] for name, v in agg["stages"].items()
        },
        "stage_agg": agg,
        "pipeline": pipeline_doc(),
    }


def _dump_flood_round_artifact(tps: float, window_s: float) -> None:
    """The --telemetry-less round artifact (ISSUE 14): the base doc,
    without the profiler fold."""
    doc = _flood_round_doc("flood", tps, window_s)
    path = _flood_artifact_path()
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    print(f"# flood round artifact -> {path}", flush=True)


def _flood_rounds_artifact_path() -> str:
    base = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(base, "bench_telemetry.flood.rounds.json")


def _dump_flood_rounds_artifact(nodes, window_s: float) -> None:
    """ISSUE 16 round artifact: the fleet observatory's view of the
    measured flood — per-consensus-phase span vector aggregated across
    every aligned round on every replica (``round_phase_ms``, the p95 per
    phase — what tool/check_perf.py diffs round over round), the
    inter-node skew percentiles of the quorum edge, and any straggler
    attributions. Also emits ``fleet_round_skew_ms_p95`` as a metric
    line. With FISCO_FLEET_OBS=0 the ledgers recorded nothing: emit the
    disabled placeholder and write no artifact (the switch must stay a
    no-op on the flood path)."""
    svc = getattr(nodes[0], "fleet", None)
    if svc is None:
        _emit(
            M_ROUND_SKEW[0], 0.0, M_ROUND_SKEW[1], 0.0,
            error="fleet observatory disabled (FISCO_FLEET_OBS=0)",
            measured=False,
        )
        return
    from fisco_bcos_tpu.observability.roundlog import rounds_doc

    # pull every replica's ledger over the wire and align with
    # record_skew=True — the flood bench is an owning aggregation path
    # (like /fleet), so the round skews land in fisco_round_skew_ms too
    ledgers, offsets = svc._peer_ledgers({"last": 64})
    rounds = rounds_doc(ledgers, offsets, last=64, record_skew=True)
    phase_samples: dict[str, list[float]] = {}
    stragglers: dict[str, int] = {}
    for rd in rounds["rounds"]:
        for per_node in rd["nodes"].values():
            for phase, ms in per_node["phases"].items():
                phase_samples.setdefault(phase, []).append(ms)
        if "straggler" in rd:
            key = str(rd["straggler"])
            stragglers[key] = stragglers.get(key, 0) + 1
    from fisco_bcos_tpu.observability.roundlog import percentile

    doc = {
        "tag": "flood",
        "window_s": round(window_s, 3),
        "rounds_aligned": len(rounds["rounds"]),
        "nodes": rounds["nodes"],
        "round_phase_ms": {
            phase: round(percentile(v, 95), 3)
            for phase, v in sorted(phase_samples.items())
        },
        "round_phase_detail": {
            phase: {
                "n": len(v),
                "p50": round(percentile(v, 50), 3),
                "p95": round(percentile(v, 95), 3),
                "max": round(max(v), 3),
            }
            for phase, v in sorted(phase_samples.items())
        },
        "skew_ms": rounds["skew_ms"],
        "stragglers": stragglers,
        "view_changes": rounds["view_changes"],
    }
    path = _flood_rounds_artifact_path()
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    p95 = rounds["skew_ms"]["p95"]
    # acceptance: the corrected quorum edge across an in-proc fleet must
    # stay under the skew budget — vs_baseline >= 1.0 passes
    budget_ms = 250.0
    _emit(
        M_ROUND_SKEW[0], p95, M_ROUND_SKEW[1],
        budget_ms / max(p95, 1e-6),
        error=None if p95 < budget_ms
        else f"round skew p95 >= {budget_ms:.0f} ms",
    )
    print(
        f"# fleet rounds: aligned={doc['rounds_aligned']} "
        f"skew_p95={p95:.2f}ms stragglers={stragglers or '{}'} -> {path}",
        flush=True,
    )


def _storage_artifact_path() -> str:
    base = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(base, "bench_telemetry.flood.storage.json")


def _dump_storage_artifact(window_s: float, alloc_top=None) -> None:
    """ISSUE 19 storage artifact: the storage observatory's view of the
    measured flood — commit-path codec bytes per block, the
    copy-amplification ratio (entries copied per durably-written row),
    per-shard 2PC prepare/commit p95, and (under --telemetry) the top
    tracemalloc allocation sites attributed to pipeline stages.
    ``storage_commit`` is the vector tool/check_perf.py diffs round over
    round (20%-relative + 5.0 absolute-floor gates). With
    FISCO_STORAGE_OBS=0 the recorder saw nothing: emit the disabled
    placeholder and write no artifact (the switch must stay a no-op on
    the flood path)."""
    from fisco_bcos_tpu.observability.roundlog import percentile
    from fisco_bcos_tpu.observability.storagelog import STORAGE

    if not STORAGE.enabled:
        _emit(
            M_STORAGE_AMP[0], 0.0, M_STORAGE_AMP[1], 0.0,
            error="storage observatory disabled (FISCO_STORAGE_OBS=0)",
            measured=False,
        )
        return
    snap = STORAGE.snapshot(last_blocks=128)
    blocks = [b for b in snap["blocks"] if not b.get("aborted")]
    n_blocks = max(len(blocks), 1)
    bytes_per_block = sum(b["bytes_encoded"] for b in blocks) / n_blocks
    copies_per_block = sum(b["entries_copied"] for b in blocks) / n_blocks
    rows_per_block = sum(b["rows_written"] for b in blocks) / n_blocks
    amp = snap["totals"]["copy_amplification_mean"]
    shard_prep = [
        ops["prepare"]["p95_ms"]
        for ops in snap["shards"].values()
        if "prepare" in ops
    ]
    shard_comm = [
        ops["commit"]["p95_ms"]
        for ops in snap["shards"].values()
        if "commit" in ops
    ]
    doc = {
        "tag": "flood",
        "window_s": round(window_s, 3),
        "blocks_measured": len(blocks),
        # the check_perf round-over-round vector — codec bytes/block sits
        # in the thousands so a +30% regression clears the 5.0 floor
        "storage_commit": {
            "codec_bytes_per_block": round(bytes_per_block, 1),
            "entries_copied_per_block": round(copies_per_block, 1),
            "shard_prepare_p95_ms": (
                round(percentile(shard_prep, 95), 3) if shard_prep else 0.0
            ),
            "shard_commit_p95_ms": (
                round(percentile(shard_comm, 95), 3) if shard_comm else 0.0
            ),
        },
        "rows_written_per_block": round(rows_per_block, 1),
        "copy_amplification": amp,
        "codec": snap["codec"],
        "copies": snap["copies"],
        "pages_rewritten": snap["pages_rewritten"],
        "shards": snap["shards"],
        "totals": snap["totals"],
        "blocks": blocks[-16:],
    }
    if alloc_top is not None:
        doc["alloc_top"] = alloc_top
    path = _storage_artifact_path()
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    _emit(M_STORAGE_AMP[0], amp, M_STORAGE_AMP[1], amp)
    top3 = ", ".join(
        f"{a['site']}={a['kib']:.0f}KiB" for a in (alloc_top or [])[:3]
    )
    print(
        f"# storage ledger: blocks={len(blocks)} "
        f"codec_bytes/block={bytes_per_block:.0f} amp={amp:.2f} "
        + (f"alloc_top=[{top3}] " if top3 else "")
        + f"-> {path}",
        flush=True,
    )


def _gate_flood_round(prev_doc: dict | None, tps: float) -> None:
    """Consecutive-round flood-TPS regression gate (ISSUE 14): diff this
    round's TPS against the previous round's artifact with the
    tool/check_perf differ (>= 20% drop fails the metric line)."""
    prev_tps = (prev_doc or {}).get("flood_tps")
    if not prev_tps:
        return
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tool"))
    import check_perf

    regressions, _notes = check_perf.diff(
        {"flood_tps": prev_tps}, {"flood_tps": tps}
    )
    ratio = tps / prev_tps
    _emit(
        "flood_tps_vs_prev_round",
        ratio,
        "x",
        ratio / 0.8,  # the 20% check_perf gate expressed as measured/required
        error="; ".join(regressions) if regressions else None,
    )


def _dump_pipeline_artifact(tag: str, tps: float, prof, window_s: float) -> None:
    """ISSUE 9 round artifact: per-stage utilization + blocked-on edges
    (the pipeline observatory snapshot), the per-stage self-time vector
    aggregated across ALL sampled txs in the flood window (what
    tool/check_perf.py diffs round over round), and the 100 Hz profiler's
    self-time/flamegraph fold with its measured duty-cycle overhead."""
    report = prof.report()
    doc = _flood_round_doc(tag, tps, window_s)
    doc["profile"] = report
    base = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(base, f"bench_telemetry.{tag}.pipeline.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    overhead_pct = report["overhead"]["duty_cycle"] * 100.0
    # acceptance: the 100 Hz profiler must cost < 5% flood TPS —
    # vs_baseline is allowed/measured so >= 1.0 passes
    _emit(
        "flood_profiler_overhead_pct",
        overhead_pct,
        "%",
        5.0 / max(overhead_pct, 1e-6),
        error=None if overhead_pct < 5.0 else "profiler duty cycle >= 5%",
    )
    stages = doc["pipeline"]["stages"]  # the SAME snapshot the artifact holds
    busiest = max(
        stages.items(), key=lambda kv: kv[1]["busy_ms"], default=(None, None)
    )[0]
    edges = sorted(
        (
            (s, on, ms)
            for s, v in stages.items()
            for on, ms in v["blocked_ms"].items()
        ),
        key=lambda e: -e[2],
    )
    top_edge = f"{edges[0][0]} blocked_on={edges[0][1]} {edges[0][2]:.0f}ms" \
        if edges else "none"
    print(
        f"# pipeline: busiest={busiest} top_blocked=[{top_edge}] "
        f"profiler_samples={report['samples']} "
        f"overhead={overhead_pct:.2f}% -> {path}",
        flush=True,
    )


def _dump_device_artifact(tag: str, window_s: float, warm_ledger) -> None:
    """ISSUE 13 round artifact: the device observatory's view of the
    MEASURED flood window — per-op queue/compile/transfer/execute phase
    vector (what tool/check_perf.py diffs round over round, execute-phase
    per op), the measured compile ledger (ideally compile-free: the warm
    round paid the compiles, kept under ``warm_round``), storm state, and
    the observatory's own measured bookkeeping overhead (< 5% of flood
    wall is the acceptance bound)."""
    from fisco_bcos_tpu.observability.device import LEDGER, compile_counts

    rows = LEDGER.snapshot()
    doc = {
        "tag": tag,
        "window_s": round(window_s, 3),
        "op_phase_ms": LEDGER.phase_totals(),
        "ledger": rows,
        "cold_compiles": sum(r["cold_compiles"] for r in rows),
        "cache_hits": sum(r["cache_hits"] for r in rows),
        "compile_counts": compile_counts(),
        "storm": LEDGER.storm_state(),
        "obs_overhead_s": round(LEDGER.overhead_seconds(), 6),
        "adjacency": LEDGER.adjacency(),
        "warm_round": warm_ledger,
    }
    base = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(base, f"bench_telemetry.{tag}.device.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    overhead_pct = doc["obs_overhead_s"] / max(window_s, 1e-9) * 100.0
    # acceptance: the device observatory must cost < 5% of flood wall —
    # vs_baseline is allowed/measured so >= 1.0 passes
    _emit(
        "flood_device_obs_overhead_pct",
        overhead_pct,
        "%",
        5.0 / max(overhead_pct, 1e-6),
        error=None if overhead_pct < 5.0 else "device observatory >= 5%",
    )
    execs = {
        op: phases.get("execute", 0.0)
        for op, phases in doc["op_phase_ms"].items()
    }
    top = max(execs.items(), key=lambda kv: kv[1], default=(None, 0.0))
    print(
        f"# device: {doc['cold_compiles']} cold compile(s) in the measured "
        f"window, {doc['cache_hits']} cache load(s), top execute "
        f"op={top[0]} ({top[1]:.0f}ms) -> {path}",
        flush=True,
    )


def _dump_telemetry(tag: str) -> None:
    """--telemetry mode: write the metrics snapshot + trace next to the
    bench JSON lines (per-child files — each --only child is its own
    process), so every perf claim ships an inspectable artifact (load the
    trace in ui.perfetto.dev)."""
    if not os.environ.get("FISCO_BENCH_TELEMETRY"):
        return
    from fisco_bcos_tpu.observability import TRACER
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    base = os.path.dirname(os.path.abspath(__file__))
    mpath = os.path.join(base, f"bench_telemetry.{tag}.metrics.txt")
    tpath = os.path.join(base, f"bench_telemetry.{tag}.trace.json")
    with open(mpath, "w") as f:
        # artifact file, not a scrape: include the OpenMetrics exemplars
        f.write(REGISTRY.render(openmetrics=True))
    with open(tpath, "w") as f:
        f.write(TRACER.export_json())
    print(f"# telemetry metrics={mpath} trace={tpath}", flush=True)
    # per-tx critical path: stitch the last committed tx's lifecycle into
    # an ordered stage breakdown with the dominant stage named — the
    # attributable-latency artifact every perf claim should ship
    from fisco_bcos_tpu.observability import critical_path

    tx = critical_path.latest_committed_tx()
    if tx is not None:
        cpath = os.path.join(base, f"bench_telemetry.{tag}.critical_path.json")
        doc = critical_path.trace_tx(tx)
        with open(cpath, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        print(
            f"# critical path tx={tx[:16]} dominant={doc.get('dominant')} "
            f"({doc.get('dominant_ms')}ms of {doc.get('total_ms')}ms) "
            f"-> {cpath}",
            flush=True,
        )


def bench_storage_child() -> None:
    """--only storage child (ISSUE 19): the bench_storage.py backend legs
    on the round cadence. Rides the parent's budget/deadline split like
    the scenario children — the leg loop stops at the deadline (a slow
    disk must yield degraded lines, never a budget-killed child) — and
    writes the per-(backend, op) rows/s vector to ``bench_storage.json``
    next to the metric lines."""
    import bench_storage

    budget = _child_budget_s()
    deadline = (
        time.monotonic() + max(budget - 15, 20)
        if budget is not None
        else None
    )
    n = int(os.environ.get("FISCO_BENCH_STORAGE_ROWS", "20000") or 20000)
    if budget is not None and budget < 60:
        # a thin slice measures fewer rows instead of risking the kill
        n = min(n, 5000)
    results = bench_storage.run(n, deadline=deadline)
    doc = {
        "n_rows": n,
        "budget_s": budget,
        "results": results,
        "rows_per_s": {
            f"{r['backend']}_{r['op']}": r["value"] for r in results
        },
    }
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_storage.json"
    )
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    print(f"# storage bench artifact -> {path}", flush=True)


def _child_budget_s() -> float | None:
    """Wall-clock budget handed to this --only child by the parent's
    deadline scheduler (None when run standalone)."""
    raw = os.environ.get("FISCO_BENCH_CHILD_BUDGET")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def _probe_backend(timeout_s: int = 240) -> bool:
    """The axon TPU tunnel sometimes goes UNAVAILABLE and hangs even
    `jax.devices()` indefinitely; probe in a killable subprocess so a dead
    tunnel costs minutes, not the whole bench budget.

    The probe must EXECUTE an op, not just enumerate devices: the tunnel
    has a half-up failure mode (seen r5) where `jax.devices()` returns
    instantly but the first dispatch hangs forever — a device-list probe
    would pass and then every bench child would hang through its whole
    budget slice."""
    import subprocess
    import sys

    try:
        res = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, jax.numpy as jnp; "
                "jnp.arange(4).sum().block_until_ready()",
            ],
            timeout=timeout_s,
            capture_output=True,
        )
        return res.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _emit_missing(error: str) -> None:
    for metric, unit in ALL_METRICS:
        if metric not in _EMITTED:
            _emit(metric, 0.0, unit, 0.0, error=error, measured=False)


def main() -> None:
    # The WHOLE bench must fit one driver budget: r4's artifact lost its
    # flood metric to the driver's `timeout` (rc=124) because per-metric
    # caps summed far beyond it. A deadline scheduler splits one explicit
    # total across the children — each child gets remaining/remaining_count,
    # so cheap children donate surplus to later ones and the final child
    # still ends before the total. Default must be conservative enough for
    # an unknown driver budget.
    import re
    import subprocess
    import sys

    t_start = time.monotonic()
    try:
        total_s = float(os.environ.get("FISCO_BENCH_TOTAL_BUDGET", "1500"))
    except ValueError:
        total_s = 1500.0  # malformed env must not cost the artifact

    if not _probe_backend(timeout_s=int(min(240, total_s / 6))):
        # tunnel down: measure every metric on CPU XLA instead of emitting
        # zeros — each line carries an explicit NOT-a-TPU-number error tag,
        # and the run still exits 2 so the driver records the degradation
        print(f"# {_CPU_FALLBACK_NOTE}", flush=True)
        os.environ["FISCO_BENCH_CPU_FALLBACK"] = "1"

    def _text(raw) -> str:
        if raw is None:
            return ""
        if isinstance(raw, bytes):  # kill can truncate mid-character
            return raw.decode(errors="replace")
        return raw

    rc = 0
    # each metric runs in its own killable subprocess: a tunnel that flaps
    # mid-run hangs inside native gRPC where no Python signal can fire
    # (the same failure mode _probe_backend isolates), so a hang must cost
    # one metric's slice, not the whole run
    # cheap-compile-first: the deadline split hands each child
    # remaining/remaining_count, so early finishers donate surplus to the
    # expensive EC children and the flood
    # (the storage child is pure host CPU — it runs second so its surplus
    # donates to the compile-heavy EC children and the flood)
    names = ["merkle", "storage", "admission", "sm2", "flood"]
    # ROADMAP frontier wired into the round cadence: the isolation
    # victim-ratio (>=0.7x acceptance) and the proof-storm read path are
    # tracked per round alongside flood TPS. FISCO_BENCH_SCENARIOS=0 opts
    # out; the children ride the same deadline split + kill machinery.
    if os.environ.get("FISCO_BENCH_SCENARIOS", "1") != "0":
        names += [
            "scenario:isolation",
            "scenario:proof-storm",
            "scenario:big-committee",
            "scenario:byzantine",
        ]
    for i, name in enumerate(names):
        remaining = total_s - (time.monotonic() - t_start) - 10  # emit reserve
        if remaining < 20:
            print(f"# bench budget exhausted before {name}", flush=True)
            break
        budget_s = remaining / (len(names) - i)
        out = err = ""
        try:
            env = dict(
                os.environ,
                FISCO_BENCH_CHILD_BUDGET=str(int(budget_s)),
                FISCO_BENCH_CHILD_NAME=name,
            )
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--only", name],
                timeout=budget_s + 15,  # grace: child self-caps first
                capture_output=True,
                env=env,
            )
            out, err = _text(res.stdout), _text(res.stderr)
            failed = bool(res.returncode)
        except subprocess.TimeoutExpired as e:
            out, err = _text(e.stdout), _text(e.stderr)
            print(f"# bench {name} timed out after {budget_s}s", flush=True)
            failed = True
            # a TPU child that timed out may mean the tunnel dropped
            # mid-bench (r5: flaps every few hours). Re-probe cheaply; if
            # it is gone, flip the REMAINING children to CPU fallback so
            # they measure something instead of hanging through their
            # slices too.
            # generous timeout (startup probe allows 240 s: cold jax import
            # + remote init + compile can near a minute on a HEALTHY
            # tunnel); skip entirely when the leftover budget can't afford
            # it — a spurious flip would mislabel the rest of the artifact
            # the probe itself may HANG for its whole timeout (that is the
            # failure mode being detected), so it must never consume the
            # runway the fallback children need: cap it well below what is
            # left, and skip when too little remains for probe + children
            avail_s = total_s - (time.monotonic() - t_start) - 30
            if (
                not os.environ.get("FISCO_BENCH_CPU_FALLBACK")
                and avail_s >= 180
                and not _probe_backend(timeout_s=int(min(240, avail_s - 120)))
            ):
                print(
                    "# tunnel lost mid-bench; remaining metrics fall back "
                    "to CPU",
                    flush=True,
                )
                os.environ["FISCO_BENCH_CPU_FALLBACK"] = "1"
        except Exception as e:  # exec failure etc. — artifact must survive
            print(f"# bench {name} could not run: {e}", flush=True)
            failed = True
        if failed:
            rc = 1
            for line in err.splitlines()[-4:]:  # surface the crash reason
                print(f"# {name} stderr: {line[:300]}", flush=True)
        for line in out.splitlines():
            if line.startswith("{") or line.startswith("#"):
                print(line, flush=True)
                m = re.search(r'"metric":\s*"([^"]+)"', line)
                if m:
                    _EMITTED.add(m.group(1))
    _emit_missing("bench raised before measuring — see '#' comment lines")
    if rc:
        raise SystemExit(rc)  # a child crashed/timed out: keep that signal
    if os.environ.get("FISCO_BENCH_CPU_FALLBACK"):
        raise SystemExit(2)  # complete, but the numbers are NOT TPU numbers
    raise SystemExit(0)


def _main_only(name: str) -> None:
    fns = {
        "admission": bench_admission,
        "sm2": bench_sm2,
        "merkle": bench_merkle,
        "flood": bench_flood,
        "storage": bench_storage_child,
    }
    if name.startswith("scenario:"):
        scen = name.split(":", 1)[1]
        _init_jax()
        try:
            bench_scenario(scen)
            _dump_telemetry(f"scenario_{scen}")
        except Exception as e:
            print(f"# bench scenario {scen} failed: {e}", flush=True)
            raise SystemExit(1)
        return
    if name not in fns:
        print(f"# unknown bench '{name}'", flush=True)
        raise SystemExit(2)
    if name != "storage":
        # the storage child is pure host CPU: skipping device init keeps
        # its slice immune to a flapped TPU tunnel
        _init_jax()
    try:
        fns[name]()
        _dump_telemetry(name)
    except Exception as e:
        print(f"# bench bench_{name} failed: {e}", flush=True)
        raise SystemExit(1)


def _main_scenario(name: str) -> None:
    """--scenario parent: run one named scenario through the same killable
    --only child machinery as the metric benches (a wedged chain or a
    flapped TPU tunnel costs this run, not the caller's whole budget)."""
    import subprocess
    import sys

    from fisco_bcos_tpu.scenario import SCENARIOS

    if name not in SCENARIOS and name not in (
        "isolation", "proof-storm", "big-committee", "byzantine",
        "byzantine-wire",
    ):
        known = ", ".join(sorted(SCENARIOS))
        print(f"# unknown scenario '{name}' (known: {known})", flush=True)
        raise SystemExit(2)
    try:
        total_s = float(os.environ.get("FISCO_BENCH_TOTAL_BUDGET", "1200"))
    except ValueError:
        total_s = 1200.0
    if not _probe_backend(timeout_s=int(min(240, total_s / 6))):
        print(f"# {_CPU_FALLBACK_NOTE}", flush=True)
        os.environ["FISCO_BENCH_CPU_FALLBACK"] = "1"
    child = f"scenario:{name}"
    env = dict(
        os.environ,
        FISCO_BENCH_CHILD_BUDGET=str(int(total_s - 20)),
        FISCO_BENCH_CHILD_NAME=child,
    )
    rc = 0
    out = err = ""
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--only", child],
            timeout=total_s + 15,
            capture_output=True,
            env=env,
        )
        out = res.stdout.decode(errors="replace")
        err = res.stderr.decode(errors="replace")
        rc = 1 if res.returncode else 0
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode(errors="replace")
        err = (e.stderr or b"").decode(errors="replace")
        print(f"# scenario {name} timed out after {total_s}s", flush=True)
        rc = 1
    for line in out.splitlines():
        if line.startswith("{") or line.startswith("#"):
            print(line, flush=True)
    if rc:
        for line in err.splitlines()[-4:]:
            print(f"# scenario stderr: {line[:300]}", flush=True)
    raise SystemExit(rc)


if __name__ == "__main__":
    import sys as _sys

    if "--telemetry" in _sys.argv:
        # telemetry artifacts feed dashboards; refuse to produce them from
        # a tree whose enforced invariants regressed (or whose accepted-debt
        # baseline went stale) — `python -m fisco_bcos_tpu.analysis` first
        from fisco_bcos_tpu.analysis import check_repo as _check_repo

        _new, _stale = _check_repo()
        if _new or _stale:
            for _f in _new:
                print(f"# analysis: {_f.render()}", flush=True)
            for _k in _stale:
                print(f"# analysis: stale baseline entry: {_k}", flush=True)
            print(
                "# --telemetry refused: static-analysis baseline has "
                f"unreviewed regressions ({len(_new)} new finding(s), "
                f"{len(_stale)} stale entr(ies))",
                flush=True,
            )
            raise SystemExit(2)
        # same refusal for the jaxpr baseline: a dashboard artifact must
        # not be produced while the committed program fingerprints don't
        # cover the inventory. Fast path — one cheap program re-traced,
        # coverage/stale checked by NAME against the full inventory
        # (`--jaxpr` re-traces everything non-slow; too slow for here).
        from fisco_bcos_tpu.analysis import progaudit as _progaudit

        _jres = _progaudit.audit(
            programs=["fisco_bcos_tpu/ops/keccak.py:keccak256_blocks"]
        )
        _jdiff = _progaudit.diff_audit(
            _jres, _progaudit.load_jaxpr_baseline()
        )
        if not _jdiff["ok"]:
            for _c in _jdiff["changed"]:
                print(
                    f"# jaxpr: CHANGED {_c['key']}: {_c['explanation']}",
                    flush=True,
                )
            for _lbl in ("new", "stale", "missing", "missing_spec"):
                for _k in _jdiff[_lbl]:
                    print(f"# jaxpr: {_lbl}: {_k}", flush=True)
            for _f in _jdiff["failures"]:
                print(
                    f"# jaxpr: failure: {_f['key']}: {_f['error']}",
                    flush=True,
                )
            print(
                "# --telemetry refused: tool/jaxpr_baseline.json is stale "
                "vs the jit inventory (python -m fisco_bcos_tpu.analysis "
                "--jaxpr, then --update-jaxpr-baseline after review)",
                flush=True,
            )
            raise SystemExit(2)
        # dump the metrics snapshot + per-block trace alongside the JSON
        # lines (propagates to --only children through the environment)
        _sys.argv.remove("--telemetry")
        os.environ["FISCO_BENCH_TELEMETRY"] = "1"
    if "--seed" in _sys.argv:
        i = _sys.argv.index("--seed")
        if i + 1 >= len(_sys.argv):
            print("usage: bench.py --scenario <name> [--seed N]")
            raise SystemExit(2)
        os.environ["FISCO_SCENARIO_SEED"] = _sys.argv[i + 1]
        del _sys.argv[i : i + 2]
    if "--scenario" in _sys.argv:
        i = _sys.argv.index("--scenario")
        if i + 1 >= len(_sys.argv):
            print("usage: bench.py [--telemetry] --scenario <name> [--seed N]")
            raise SystemExit(2)
        _main_scenario(_sys.argv[i + 1])
    elif len(_sys.argv) >= 2 and _sys.argv[1] == "--only":
        if len(_sys.argv) < 3:
            print(
                "usage: bench.py [--telemetry] "
                "[--only admission|sm2|merkle|flood|storage|scenario:<name>] "
                "[--scenario <name> [--seed N]]"
            )
            raise SystemExit(2)
        _main_only(_sys.argv[2])
    else:
        main()
