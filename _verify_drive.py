import jax
jax.config.update("jax_platforms", "cpu")
from fisco_bcos_tpu.crypto.suite import ecdsa_suite
from fisco_bcos_tpu.executor import TransactionExecutor
from fisco_bcos_tpu.executor.evm import contract_table
from fisco_bcos_tpu.protocol.block_header import BlockHeader
from fisco_bcos_tpu.protocol.transaction import Transaction
from fisco_bcos_tpu.scheduler.dmc import DMCScheduler, ExecutorShard
from fisco_bcos_tpu.storage import MemoryStorage

import sys
sys.path.insert(0, "tests")
from evm_asm import _deployer, counter_runtime, pingpong_runtime

suite = ecdsa_suite()
ex = TransactionExecutor(MemoryStorage(), suite)
ex.next_block_header(BlockHeader(number=1, timestamp=1700000000))

rc = ex.execute_transactions([Transaction(to=b"", input=_deployer(counter_runtime(ex.codec)), sender=b"\x11"*20)])[0]
assert rc.status == 0, rc.output
addr = rc.contract_address
for _ in range(3):
    r = ex.execute_transactions([Transaction(to=addr, input=ex.codec.selector("inc()"), sender=b"\x11"*20)])[0]
    assert r.status == 0
out = ex.execute_transactions([Transaction(to=addr, input=ex.codec.selector("get()"), sender=b"\x11"*20)])[0]
assert int.from_bytes(out.output, "big") == 3
print("EVM deploy+call: counter == 3 OK", flush=True)

rcs = ex.execute_transactions([
    Transaction(to=b"", input=_deployer(pingpong_runtime()), sender=b"\x11"*20),
    Transaction(to=b"", input=_deployer(pingpong_runtime()), sender=b"\x11"*20),
])
a, b = rcs[0].contract_address, rcs[1].contract_address
s1 = ExecutorShard(ex, "shard1", owns=lambda c: c != b)
s2 = ExecutorShard(ex, "shard2", owns=lambda c: c == b)
sched = DMCScheduler(lambda c: s2 if c == b else s1)
t1 = Transaction(to=a, input=b"\x00"*12 + b, sender=b"\xbb"*20)
t2 = Transaction(to=b, input=b"\x00"*12 + a, sender=b"\xcc"*20)
receipts = sched.execute([t1, t2])
assert receipts[0].status == 0, receipts[0].output
assert receipts[1].output == b"deadlock victim", (receipts[1].status, receipts[1].output)
row_a = ex._block.storage.get_row(contract_table(a), (0).to_bytes(32, "big"))
row_b = ex._block.storage.get_row(contract_table(b), (0).to_bytes(32, "big"))
assert int.from_bytes(row_a.get(), "big") == 1 and int.from_bytes(row_b.get(), "big") == 1
print(f"DMC: cross-shard migration {sched.recorder.round} rounds; deadlock victim reverted OK", flush=True)
print("VERIFY PASS")
