// Host-native crypto core — the wedpr-FFI/OpenSSL-EVP analog.
//
// Reference role: bcos-crypto's native hashers (hasher/OpenSSLHasher.h —
// keccak256/sha256/sm3 via EVP) and symmetric ciphers (encrypt/SM4Crypto.cpp)
// are C/C++/Rust behind FFI. This framework keeps BATCH crypto on the TPU
// (ops/*.py); the per-item host paths — PBFT packet digests, single-tx RPC
// admission, merkle spot checks, at-rest storage encryption — bind here via
// ctypes (fisco_bcos_tpu/native_bind.py), with the pure-Python crypto/ref
// implementations as the always-available fallback and golden reference.
//
// Build: g++ -O2 -shared -fPIC -o libfisco_native.so fisco_native.cpp

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ===========================================================================
// Keccak-256 (Keccak-f[1600], rate 136, 0x01 domain padding — Ethereum/FISCO
// tx-hash variant, matching crypto/ref/keccak.py)
// ===========================================================================

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

static const int KECCAK_ROT[25] = {
    0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10, 43,
    25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56, 14,
};

static inline uint64_t rotl64(uint64_t x, int n) {
    return n == 0 ? x : (x << n) | (x >> (64 - n));
}

static void keccak_f1600(uint64_t st[25]) {
    for (int round = 0; round < 24; round++) {
        // theta
        uint64_t bc[5];
        for (int x = 0; x < 5; x++)
            bc[x] = st[x] ^ st[x + 5] ^ st[x + 10] ^ st[x + 15] ^ st[x + 20];
        for (int x = 0; x < 5; x++) {
            uint64_t d = bc[(x + 4) % 5] ^ rotl64(bc[(x + 1) % 5], 1);
            for (int y = 0; y < 25; y += 5) st[x + y] ^= d;
        }
        // rho + pi
        uint64_t b[25];
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++)
                // B[y, (2x+3y) mod 5] = rot(A[x, y]) with A indexed x + 5y
                b[y + 5 * ((2 * x + 3 * y) % 5)] =
                    rotl64(st[x + 5 * y], KECCAK_ROT[x + 5 * y]);
        // chi
        for (int y = 0; y < 25; y += 5)
            for (int x = 0; x < 5; x++)
                st[x + y] = b[x + y] ^ ((~b[(x + 1) % 5 + y]) & b[(x + 2) % 5 + y]);
        // iota
        st[0] ^= KECCAK_RC[round];
    }
}

void fisco_keccak256(const uint8_t* data, size_t len, uint8_t out[32]) {
    const size_t rate = 136;
    uint64_t st[25];
    std::memset(st, 0, sizeof(st));
    // absorb
    while (len >= rate) {
        for (size_t i = 0; i < rate / 8; i++) {
            uint64_t lane;
            std::memcpy(&lane, data + 8 * i, 8);
            st[i] ^= lane;  // little-endian hosts only (x86/arm64)
        }
        keccak_f1600(st);
        data += rate;
        len -= rate;
    }
    // final block with 0x01 .. 0x80 padding
    uint8_t block[136];
    std::memset(block, 0, rate);
    std::memcpy(block, data, len);
    block[len] = 0x01;
    block[rate - 1] |= 0x80;
    for (size_t i = 0; i < rate / 8; i++) {
        uint64_t lane;
        std::memcpy(&lane, block + 8 * i, 8);
        st[i] ^= lane;
    }
    keccak_f1600(st);
    std::memcpy(out, st, 32);
}

// ===========================================================================
// SHA-256 (FIPS 180-4)
// ===========================================================================

static const uint32_t SHA256_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

static inline uint32_t rotr32(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

static void sha256_block(uint32_t h[8], const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
               (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
        uint32_t ch = (e & f) ^ ((~e) & g);
        uint32_t t1 = hh + S1 + ch + SHA256_K[i] + w[i];
        uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        hh = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

void fisco_sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
    uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                     0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    size_t full = len / 64;
    for (size_t i = 0; i < full; i++) sha256_block(h, data + 64 * i);
    uint8_t tail[128];
    size_t rem = len - 64 * full;
    std::memcpy(tail, data + 64 * full, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    std::memset(tail + rem + 1, 0, tail_len - rem - 1);
    uint64_t bits = uint64_t(len) * 8;
    for (int i = 0; i < 8; i++)
        tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
    sha256_block(h, tail);
    if (tail_len == 128) sha256_block(h, tail + 64);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = uint8_t(h[i] >> 24);
        out[4 * i + 1] = uint8_t(h[i] >> 16);
        out[4 * i + 2] = uint8_t(h[i] >> 8);
        out[4 * i + 3] = uint8_t(h[i]);
    }
}

// ===========================================================================
// SM3 (GB/T 32905-2016)
// ===========================================================================

static inline uint32_t rotl32(uint32_t x, int n) {
    n &= 31;
    return n == 0 ? x : (x << n) | (x >> (32 - n));
}

static void sm3_block(uint32_t v[8], const uint8_t* p) {
    uint32_t w[68], w1[64];
    for (int i = 0; i < 16; i++)
        w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
               (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 68; i++) {
        uint32_t x = w[i - 16] ^ w[i - 9] ^ rotl32(w[i - 3], 15);
        x = x ^ rotl32(x, 15) ^ rotl32(x, 23);  // P1
        w[i] = x ^ rotl32(w[i - 13], 7) ^ w[i - 6];
    }
    for (int i = 0; i < 64; i++) w1[i] = w[i] ^ w[i + 4];
    uint32_t a = v[0], b = v[1], c = v[2], d = v[3];
    uint32_t e = v[4], f = v[5], g = v[6], h = v[7];
    for (int i = 0; i < 64; i++) {
        uint32_t t = (i < 16) ? 0x79cc4519 : 0x7a879d8a;
        uint32_t ss1 = rotl32(rotl32(a, 12) + e + rotl32(t, i), 7);
        uint32_t ss2 = ss1 ^ rotl32(a, 12);
        uint32_t ff = (i < 16) ? (a ^ b ^ c) : ((a & b) | (a & c) | (b & c));
        uint32_t gg = (i < 16) ? (e ^ f ^ g) : ((e & f) | ((~e) & g));
        uint32_t tt1 = ff + d + ss2 + w1[i];
        uint32_t tt2 = gg + h + ss1 + w[i];
        d = c;
        c = rotl32(b, 9);
        b = a;
        a = tt1;
        h = g;
        g = rotl32(f, 19);
        f = e;
        uint32_t p0 = tt2 ^ rotl32(tt2, 9) ^ rotl32(tt2, 17);  // P0
        e = p0;
    }
    v[0] ^= a; v[1] ^= b; v[2] ^= c; v[3] ^= d;
    v[4] ^= e; v[5] ^= f; v[6] ^= g; v[7] ^= h;
}

void fisco_sm3(const uint8_t* data, size_t len, uint8_t out[32]) {
    uint32_t v[8] = {0x7380166f, 0x4914b2b9, 0x172442d7, 0xda8a0600,
                     0xa96f30bc, 0x163138aa, 0xe38dee4d, 0xb0fb0e4e};
    size_t full = len / 64;
    for (size_t i = 0; i < full; i++) sm3_block(v, data + 64 * i);
    uint8_t tail[128];
    size_t rem = len - 64 * full;
    std::memcpy(tail, data + 64 * full, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    std::memset(tail + rem + 1, 0, tail_len - rem - 1);
    uint64_t bits = uint64_t(len) * 8;
    for (int i = 0; i < 8; i++)
        tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
    sm3_block(v, tail);
    if (tail_len == 128) sm3_block(v, tail + 64);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = uint8_t(v[i] >> 24);
        out[4 * i + 1] = uint8_t(v[i] >> 16);
        out[4 * i + 2] = uint8_t(v[i] >> 8);
        out[4 * i + 3] = uint8_t(v[i]);
    }
}

// ===========================================================================
// SM4 (GB/T 32907-2016) — block + CBC (no padding; callers do PKCS7)
// ===========================================================================

static const uint8_t SM4_SBOX[256] = {
    0xd6, 0x90, 0xe9, 0xfe, 0xcc, 0xe1, 0x3d, 0xb7, 0x16, 0xb6, 0x14, 0xc2,
    0x28, 0xfb, 0x2c, 0x05, 0x2b, 0x67, 0x9a, 0x76, 0x2a, 0xbe, 0x04, 0xc3,
    0xaa, 0x44, 0x13, 0x26, 0x49, 0x86, 0x06, 0x99, 0x9c, 0x42, 0x50, 0xf4,
    0x91, 0xef, 0x98, 0x7a, 0x33, 0x54, 0x0b, 0x43, 0xed, 0xcf, 0xac, 0x62,
    0xe4, 0xb3, 0x1c, 0xa9, 0xc9, 0x08, 0xe8, 0x95, 0x80, 0xdf, 0x94, 0xfa,
    0x75, 0x8f, 0x3f, 0xa6, 0x47, 0x07, 0xa7, 0xfc, 0xf3, 0x73, 0x17, 0xba,
    0x83, 0x59, 0x3c, 0x19, 0xe6, 0x85, 0x4f, 0xa8, 0x68, 0x6b, 0x81, 0xb2,
    0x71, 0x64, 0xda, 0x8b, 0xf8, 0xeb, 0x0f, 0x4b, 0x70, 0x56, 0x9d, 0x35,
    0x1e, 0x24, 0x0e, 0x5e, 0x63, 0x58, 0xd1, 0xa2, 0x25, 0x22, 0x7c, 0x3b,
    0x01, 0x21, 0x78, 0x87, 0xd4, 0x00, 0x46, 0x57, 0x9f, 0xd3, 0x27, 0x52,
    0x4c, 0x36, 0x02, 0xe7, 0xa0, 0xc4, 0xc8, 0x9e, 0xea, 0xbf, 0x8a, 0xd2,
    0x40, 0xc7, 0x38, 0xb5, 0xa3, 0xf7, 0xf2, 0xce, 0xf9, 0x61, 0x15, 0xa1,
    0xe0, 0xae, 0x5d, 0xa4, 0x9b, 0x34, 0x1a, 0x55, 0xad, 0x93, 0x32, 0x30,
    0xf5, 0x8c, 0xb1, 0xe3, 0x1d, 0xf6, 0xe2, 0x2e, 0x82, 0x66, 0xca, 0x60,
    0xc0, 0x29, 0x23, 0xab, 0x0d, 0x53, 0x4e, 0x6f, 0xd5, 0xdb, 0x37, 0x45,
    0xde, 0xfd, 0x8e, 0x2f, 0x03, 0xff, 0x6a, 0x72, 0x6d, 0x6c, 0x5b, 0x51,
    0x8d, 0x1b, 0xaf, 0x92, 0xbb, 0xdd, 0xbc, 0x7f, 0x11, 0xd9, 0x5c, 0x41,
    0x1f, 0x10, 0x5a, 0xd8, 0x0a, 0xc1, 0x31, 0x88, 0xa5, 0xcd, 0x7b, 0xbd,
    0x2d, 0x74, 0xd0, 0x12, 0xb8, 0xe5, 0xb4, 0xb0, 0x89, 0x69, 0x97, 0x4a,
    0x0c, 0x96, 0x77, 0x7e, 0x65, 0xb9, 0xf1, 0x09, 0xc5, 0x6e, 0xc6, 0x84,
    0x18, 0xf0, 0x7d, 0xec, 0x3a, 0xdc, 0x4d, 0x20, 0x79, 0xee, 0x5f, 0x3e,
    0xd7, 0xcb, 0x39, 0x48,
};

static const uint32_t SM4_FK[4] = {0xa3b1bac6, 0x56aa3350, 0x677d9197,
                                   0xb27022dc};

static inline uint32_t sm4_tau(uint32_t a) {
    return (uint32_t(SM4_SBOX[(a >> 24) & 0xff]) << 24) |
           (uint32_t(SM4_SBOX[(a >> 16) & 0xff]) << 16) |
           (uint32_t(SM4_SBOX[(a >> 8) & 0xff]) << 8) |
           uint32_t(SM4_SBOX[a & 0xff]);
}

static void sm4_expand(const uint8_t key[16], uint32_t rk[32]) {
    uint32_t k[4];
    for (int i = 0; i < 4; i++)
        k[i] = ((uint32_t(key[4 * i]) << 24) | (uint32_t(key[4 * i + 1]) << 16) |
                (uint32_t(key[4 * i + 2]) << 8) | uint32_t(key[4 * i + 3])) ^
               SM4_FK[i];
    for (int i = 0; i < 32; i++) {
        uint32_t ck = 0;
        for (int j = 0; j < 4; j++) ck = (ck << 8) | uint32_t((4 * i + j) * 7 % 256);
        uint32_t b = sm4_tau(k[(i + 1) % 4] ^ k[(i + 2) % 4] ^ k[(i + 3) % 4] ^ ck);
        uint32_t nk = k[i % 4] ^ (b ^ rotl32(b, 13) ^ rotl32(b, 23));
        k[i % 4] = nk;
        rk[i] = nk;
    }
}

static void sm4_crypt_block(const uint32_t rk[32], const uint8_t in[16],
                            uint8_t out[16], int decrypt) {
    uint32_t x[4];
    for (int i = 0; i < 4; i++)
        x[i] = (uint32_t(in[4 * i]) << 24) | (uint32_t(in[4 * i + 1]) << 16) |
               (uint32_t(in[4 * i + 2]) << 8) | uint32_t(in[4 * i + 3]);
    for (int i = 0; i < 32; i++) {
        uint32_t r = decrypt ? rk[31 - i] : rk[i];
        uint32_t b = sm4_tau(x[1] ^ x[2] ^ x[3] ^ r);
        uint32_t t = x[0] ^ (b ^ rotl32(b, 2) ^ rotl32(b, 10) ^ rotl32(b, 18) ^
                             rotl32(b, 24));
        x[0] = x[1]; x[1] = x[2]; x[2] = x[3]; x[3] = t;
    }
    uint32_t y[4] = {x[3], x[2], x[1], x[0]};
    for (int i = 0; i < 4; i++) {
        out[4 * i] = uint8_t(y[i] >> 24);
        out[4 * i + 1] = uint8_t(y[i] >> 16);
        out[4 * i + 2] = uint8_t(y[i] >> 8);
        out[4 * i + 3] = uint8_t(y[i]);
    }
}

void fisco_sm4_cbc(const uint8_t key[16], const uint8_t iv[16],
                   const uint8_t* in, size_t nblocks, uint8_t* out,
                   int decrypt) {
    uint32_t rk[32];
    sm4_expand(key, rk);
    uint8_t prev[16];
    std::memcpy(prev, iv, 16);
    if (!decrypt) {
        for (size_t i = 0; i < nblocks; i++) {
            uint8_t blk[16];
            for (int j = 0; j < 16; j++) blk[j] = in[16 * i + j] ^ prev[j];
            sm4_crypt_block(rk, blk, out + 16 * i, 0);
            std::memcpy(prev, out + 16 * i, 16);
        }
    } else {
        for (size_t i = 0; i < nblocks; i++) {
            uint8_t pt[16];
            sm4_crypt_block(rk, in + 16 * i, pt, 1);
            for (int j = 0; j < 16; j++) out[16 * i + j] = pt[j] ^ prev[j];
            std::memcpy(prev, in + 16 * i, 16);
        }
    }
}

}  // extern "C"
