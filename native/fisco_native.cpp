// Host-native crypto core — the wedpr-FFI/OpenSSL-EVP analog.
//
// Reference role: bcos-crypto's native hashers (hasher/OpenSSLHasher.h —
// keccak256/sha256/sm3 via EVP) and symmetric ciphers (encrypt/SM4Crypto.cpp)
// are C/C++/Rust behind FFI. This framework keeps BATCH crypto on the TPU
// (ops/*.py); the per-item host paths — PBFT packet digests, single-tx RPC
// admission, merkle spot checks, at-rest storage encryption — bind here via
// ctypes (fisco_bcos_tpu/native_bind.py), with the pure-Python crypto/ref
// implementations as the always-available fallback and golden reference.
//
// Build: g++ -O3 -march=native -funroll-loops -shared -fPIC \
//            -o libfisco_native.so fisco_native.cpp

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <vector>

extern "C" {

// ===========================================================================
// Keccak-256 (Keccak-f[1600], rate 136, 0x01 domain padding — Ethereum/FISCO
// tx-hash variant, matching crypto/ref/keccak.py)
// ===========================================================================

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

static const int KECCAK_ROT[25] = {
    0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10, 43,
    25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56, 14,
};

static inline uint64_t rotl64(uint64_t x, int n) {
    return n == 0 ? x : (x << n) | (x >> (64 - n));
}

static void keccak_f1600(uint64_t st[25]) {
    for (int round = 0; round < 24; round++) {
        // theta
        uint64_t bc[5];
        for (int x = 0; x < 5; x++)
            bc[x] = st[x] ^ st[x + 5] ^ st[x + 10] ^ st[x + 15] ^ st[x + 20];
        for (int x = 0; x < 5; x++) {
            uint64_t d = bc[(x + 4) % 5] ^ rotl64(bc[(x + 1) % 5], 1);
            for (int y = 0; y < 25; y += 5) st[x + y] ^= d;
        }
        // rho + pi
        uint64_t b[25];
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++)
                // B[y, (2x+3y) mod 5] = rot(A[x, y]) with A indexed x + 5y
                b[y + 5 * ((2 * x + 3 * y) % 5)] =
                    rotl64(st[x + 5 * y], KECCAK_ROT[x + 5 * y]);
        // chi
        for (int y = 0; y < 25; y += 5)
            for (int x = 0; x < 5; x++)
                st[x + y] = b[x + y] ^ ((~b[(x + 1) % 5 + y]) & b[(x + 2) % 5 + y]);
        // iota
        st[0] ^= KECCAK_RC[round];
    }
}

void fisco_keccak256(const uint8_t* data, size_t len, uint8_t out[32]) {
    const size_t rate = 136;
    uint64_t st[25];
    std::memset(st, 0, sizeof(st));
    // absorb
    while (len >= rate) {
        for (size_t i = 0; i < rate / 8; i++) {
            uint64_t lane;
            std::memcpy(&lane, data + 8 * i, 8);
            st[i] ^= lane;  // little-endian hosts only (x86/arm64)
        }
        keccak_f1600(st);
        data += rate;
        len -= rate;
    }
    // final block with 0x01 .. 0x80 padding
    uint8_t block[136];
    std::memset(block, 0, rate);
    std::memcpy(block, data, len);
    block[len] = 0x01;
    block[rate - 1] |= 0x80;
    for (size_t i = 0; i < rate / 8; i++) {
        uint64_t lane;
        std::memcpy(&lane, block + 8 * i, 8);
        st[i] ^= lane;
    }
    keccak_f1600(st);
    std::memcpy(out, st, 32);
}

// ===========================================================================
// SHA-256 (FIPS 180-4)
// ===========================================================================

static const uint32_t SHA256_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

static inline uint32_t rotr32(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

static void sha256_block(uint32_t h[8], const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
               (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
        uint32_t ch = (e & f) ^ ((~e) & g);
        uint32_t t1 = hh + S1 + ch + SHA256_K[i] + w[i];
        uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        hh = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

void fisco_sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
    uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                     0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    size_t full = len / 64;
    for (size_t i = 0; i < full; i++) sha256_block(h, data + 64 * i);
    uint8_t tail[128];
    size_t rem = len - 64 * full;
    std::memcpy(tail, data + 64 * full, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    std::memset(tail + rem + 1, 0, tail_len - rem - 1);
    uint64_t bits = uint64_t(len) * 8;
    for (int i = 0; i < 8; i++)
        tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
    sha256_block(h, tail);
    if (tail_len == 128) sha256_block(h, tail + 64);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = uint8_t(h[i] >> 24);
        out[4 * i + 1] = uint8_t(h[i] >> 16);
        out[4 * i + 2] = uint8_t(h[i] >> 8);
        out[4 * i + 3] = uint8_t(h[i]);
    }
}

// ===========================================================================
// SM3 (GB/T 32905-2016)
// ===========================================================================

static inline uint32_t rotl32(uint32_t x, int n) {
    n &= 31;
    return n == 0 ? x : (x << n) | (x >> (32 - n));
}

static void sm3_block(uint32_t v[8], const uint8_t* p) {
    uint32_t w[68], w1[64];
    for (int i = 0; i < 16; i++)
        w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
               (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 68; i++) {
        uint32_t x = w[i - 16] ^ w[i - 9] ^ rotl32(w[i - 3], 15);
        x = x ^ rotl32(x, 15) ^ rotl32(x, 23);  // P1
        w[i] = x ^ rotl32(w[i - 13], 7) ^ w[i - 6];
    }
    for (int i = 0; i < 64; i++) w1[i] = w[i] ^ w[i + 4];
    uint32_t a = v[0], b = v[1], c = v[2], d = v[3];
    uint32_t e = v[4], f = v[5], g = v[6], h = v[7];
    for (int i = 0; i < 64; i++) {
        uint32_t t = (i < 16) ? 0x79cc4519 : 0x7a879d8a;
        uint32_t ss1 = rotl32(rotl32(a, 12) + e + rotl32(t, i), 7);
        uint32_t ss2 = ss1 ^ rotl32(a, 12);
        uint32_t ff = (i < 16) ? (a ^ b ^ c) : ((a & b) | (a & c) | (b & c));
        uint32_t gg = (i < 16) ? (e ^ f ^ g) : ((e & f) | ((~e) & g));
        uint32_t tt1 = ff + d + ss2 + w1[i];
        uint32_t tt2 = gg + h + ss1 + w[i];
        d = c;
        c = rotl32(b, 9);
        b = a;
        a = tt1;
        h = g;
        g = rotl32(f, 19);
        f = e;
        uint32_t p0 = tt2 ^ rotl32(tt2, 9) ^ rotl32(tt2, 17);  // P0
        e = p0;
    }
    v[0] ^= a; v[1] ^= b; v[2] ^= c; v[3] ^= d;
    v[4] ^= e; v[5] ^= f; v[6] ^= g; v[7] ^= h;
}

void fisco_sm3(const uint8_t* data, size_t len, uint8_t out[32]) {
    uint32_t v[8] = {0x7380166f, 0x4914b2b9, 0x172442d7, 0xda8a0600,
                     0xa96f30bc, 0x163138aa, 0xe38dee4d, 0xb0fb0e4e};
    size_t full = len / 64;
    for (size_t i = 0; i < full; i++) sm3_block(v, data + 64 * i);
    uint8_t tail[128];
    size_t rem = len - 64 * full;
    std::memcpy(tail, data + 64 * full, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    std::memset(tail + rem + 1, 0, tail_len - rem - 1);
    uint64_t bits = uint64_t(len) * 8;
    for (int i = 0; i < 8; i++)
        tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
    sm3_block(v, tail);
    if (tail_len == 128) sm3_block(v, tail + 64);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = uint8_t(v[i] >> 24);
        out[4 * i + 1] = uint8_t(v[i] >> 16);
        out[4 * i + 2] = uint8_t(v[i] >> 8);
        out[4 * i + 3] = uint8_t(v[i]);
    }
}

// ===========================================================================
// SM4 (GB/T 32907-2016) — block + CBC (no padding; callers do PKCS7)
// ===========================================================================

static const uint8_t SM4_SBOX[256] = {
    0xd6, 0x90, 0xe9, 0xfe, 0xcc, 0xe1, 0x3d, 0xb7, 0x16, 0xb6, 0x14, 0xc2,
    0x28, 0xfb, 0x2c, 0x05, 0x2b, 0x67, 0x9a, 0x76, 0x2a, 0xbe, 0x04, 0xc3,
    0xaa, 0x44, 0x13, 0x26, 0x49, 0x86, 0x06, 0x99, 0x9c, 0x42, 0x50, 0xf4,
    0x91, 0xef, 0x98, 0x7a, 0x33, 0x54, 0x0b, 0x43, 0xed, 0xcf, 0xac, 0x62,
    0xe4, 0xb3, 0x1c, 0xa9, 0xc9, 0x08, 0xe8, 0x95, 0x80, 0xdf, 0x94, 0xfa,
    0x75, 0x8f, 0x3f, 0xa6, 0x47, 0x07, 0xa7, 0xfc, 0xf3, 0x73, 0x17, 0xba,
    0x83, 0x59, 0x3c, 0x19, 0xe6, 0x85, 0x4f, 0xa8, 0x68, 0x6b, 0x81, 0xb2,
    0x71, 0x64, 0xda, 0x8b, 0xf8, 0xeb, 0x0f, 0x4b, 0x70, 0x56, 0x9d, 0x35,
    0x1e, 0x24, 0x0e, 0x5e, 0x63, 0x58, 0xd1, 0xa2, 0x25, 0x22, 0x7c, 0x3b,
    0x01, 0x21, 0x78, 0x87, 0xd4, 0x00, 0x46, 0x57, 0x9f, 0xd3, 0x27, 0x52,
    0x4c, 0x36, 0x02, 0xe7, 0xa0, 0xc4, 0xc8, 0x9e, 0xea, 0xbf, 0x8a, 0xd2,
    0x40, 0xc7, 0x38, 0xb5, 0xa3, 0xf7, 0xf2, 0xce, 0xf9, 0x61, 0x15, 0xa1,
    0xe0, 0xae, 0x5d, 0xa4, 0x9b, 0x34, 0x1a, 0x55, 0xad, 0x93, 0x32, 0x30,
    0xf5, 0x8c, 0xb1, 0xe3, 0x1d, 0xf6, 0xe2, 0x2e, 0x82, 0x66, 0xca, 0x60,
    0xc0, 0x29, 0x23, 0xab, 0x0d, 0x53, 0x4e, 0x6f, 0xd5, 0xdb, 0x37, 0x45,
    0xde, 0xfd, 0x8e, 0x2f, 0x03, 0xff, 0x6a, 0x72, 0x6d, 0x6c, 0x5b, 0x51,
    0x8d, 0x1b, 0xaf, 0x92, 0xbb, 0xdd, 0xbc, 0x7f, 0x11, 0xd9, 0x5c, 0x41,
    0x1f, 0x10, 0x5a, 0xd8, 0x0a, 0xc1, 0x31, 0x88, 0xa5, 0xcd, 0x7b, 0xbd,
    0x2d, 0x74, 0xd0, 0x12, 0xb8, 0xe5, 0xb4, 0xb0, 0x89, 0x69, 0x97, 0x4a,
    0x0c, 0x96, 0x77, 0x7e, 0x65, 0xb9, 0xf1, 0x09, 0xc5, 0x6e, 0xc6, 0x84,
    0x18, 0xf0, 0x7d, 0xec, 0x3a, 0xdc, 0x4d, 0x20, 0x79, 0xee, 0x5f, 0x3e,
    0xd7, 0xcb, 0x39, 0x48,
};

static const uint32_t SM4_FK[4] = {0xa3b1bac6, 0x56aa3350, 0x677d9197,
                                   0xb27022dc};

static inline uint32_t sm4_tau(uint32_t a) {
    return (uint32_t(SM4_SBOX[(a >> 24) & 0xff]) << 24) |
           (uint32_t(SM4_SBOX[(a >> 16) & 0xff]) << 16) |
           (uint32_t(SM4_SBOX[(a >> 8) & 0xff]) << 8) |
           uint32_t(SM4_SBOX[a & 0xff]);
}

static void sm4_expand(const uint8_t key[16], uint32_t rk[32]) {
    uint32_t k[4];
    for (int i = 0; i < 4; i++)
        k[i] = ((uint32_t(key[4 * i]) << 24) | (uint32_t(key[4 * i + 1]) << 16) |
                (uint32_t(key[4 * i + 2]) << 8) | uint32_t(key[4 * i + 3])) ^
               SM4_FK[i];
    for (int i = 0; i < 32; i++) {
        uint32_t ck = 0;
        for (int j = 0; j < 4; j++) ck = (ck << 8) | uint32_t((4 * i + j) * 7 % 256);
        uint32_t b = sm4_tau(k[(i + 1) % 4] ^ k[(i + 2) % 4] ^ k[(i + 3) % 4] ^ ck);
        uint32_t nk = k[i % 4] ^ (b ^ rotl32(b, 13) ^ rotl32(b, 23));
        k[i % 4] = nk;
        rk[i] = nk;
    }
}

static void sm4_crypt_block(const uint32_t rk[32], const uint8_t in[16],
                            uint8_t out[16], int decrypt) {
    uint32_t x[4];
    for (int i = 0; i < 4; i++)
        x[i] = (uint32_t(in[4 * i]) << 24) | (uint32_t(in[4 * i + 1]) << 16) |
               (uint32_t(in[4 * i + 2]) << 8) | uint32_t(in[4 * i + 3]);
    for (int i = 0; i < 32; i++) {
        uint32_t r = decrypt ? rk[31 - i] : rk[i];
        uint32_t b = sm4_tau(x[1] ^ x[2] ^ x[3] ^ r);
        uint32_t t = x[0] ^ (b ^ rotl32(b, 2) ^ rotl32(b, 10) ^ rotl32(b, 18) ^
                             rotl32(b, 24));
        x[0] = x[1]; x[1] = x[2]; x[2] = x[3]; x[3] = t;
    }
    uint32_t y[4] = {x[3], x[2], x[1], x[0]};
    for (int i = 0; i < 4; i++) {
        out[4 * i] = uint8_t(y[i] >> 24);
        out[4 * i + 1] = uint8_t(y[i] >> 16);
        out[4 * i + 2] = uint8_t(y[i] >> 8);
        out[4 * i + 3] = uint8_t(y[i]);
    }
}

void fisco_sm4_cbc(const uint8_t key[16], const uint8_t iv[16],
                   const uint8_t* in, size_t nblocks, uint8_t* out,
                   int decrypt) {
    uint32_t rk[32];
    sm4_expand(key, rk);
    uint8_t prev[16];
    std::memcpy(prev, iv, 16);
    if (!decrypt) {
        for (size_t i = 0; i < nblocks; i++) {
            uint8_t blk[16];
            for (int j = 0; j < 16; j++) blk[j] = in[16 * i + j] ^ prev[j];
            sm4_crypt_block(rk, blk, out + 16 * i, 0);
            std::memcpy(prev, out + 16 * i, 16);
        }
    } else {
        for (size_t i = 0; i < nblocks; i++) {
            uint8_t pt[16];
            sm4_crypt_block(rk, in + 16 * i, pt, 1);
            for (int j = 0; j < 16; j++) out[16 * i + j] = pt[j] ^ prev[j];
            std::memcpy(prev, in + 16 * i, 16);
        }
    }
}

// ===========================================================================
// 256-bit elliptic-curve engine: secp256k1 ECDSA (sign/verify/recover) and
// SM2 (GB/T 32918.2) sign/verify.
//
// Reference role: the wedpr-Rust FFI (wedpr_secp256k1_* at
// bcos-crypto/signature/secp256k1/Secp256k1Crypto.cpp:32-136) and the
// OpenSSL-tassl SM2 path (signature/sm2/SM2Crypto.cpp:29-91, fastsm2) — the
// reference signs/verifies every consensus packet and single-tx RPC
// admission through native code; this gives the framework the same per-item
// latency class.  Bit-identical to the pure-Python golden reference
// (fisco_bcos_tpu/crypto/ref/ecdsa.py), including RFC 6979 deterministic
// nonces with the same retry-counter derivation.
//
// Design: 4x64-bit limbs, Montgomery multiplication (CIOS) with
// unsigned __int128 products; Jacobian coordinates with the generic-a group
// law (secp a=0, SM2 a=-3 both flow through it); Strauss–Shamir interleaved
// double-scalar multiplication with 4-bit windows for the verify equations.
//
// SECURITY NOTE — not constant-time. The signing-path scalar multiply
// branches on nonce nibbles and skips leading-zero doublings, so precise
// timing/cache observation of many sign() calls leaks nonce MSB structure
// (lattice-attack material). This diverges from the hardened wedpr/OpenSSL
// signers the reference uses. Acceptable for the framework's trust model
// (consortium nodes sign on machines they own, verification — the hot
// adversarial-input path — has no secret-dependent branching on secrets it
// doesn't hold), but do NOT expose sign() as a service to untrusted
// co-tenants without moving to a constant-time ladder.
// ===========================================================================

namespace {

typedef unsigned __int128 u128;

struct U256 {
    uint64_t w[4];  // little-endian limbs
};

static const U256 U256_ZERO = {{0, 0, 0, 0}};

static inline U256 u256_load_be(const uint8_t in[32]) {
    U256 r;
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | in[8 * (3 - i) + j];
        r.w[i] = v;
    }
    return r;
}

static inline void u256_store_be(const U256& a, uint8_t out[32]) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[8 * (3 - i) + j] = uint8_t(a.w[i] >> (8 * (7 - j)));
}

static inline bool u256_is_zero(const U256& a) {
    return (a.w[0] | a.w[1] | a.w[2] | a.w[3]) == 0;
}

static inline bool u256_eq(const U256& a, const U256& b) {
    return a.w[0] == b.w[0] && a.w[1] == b.w[1] && a.w[2] == b.w[2] &&
           a.w[3] == b.w[3];
}

// -1 / 0 / +1 for a<b / a==b / a>b
static inline int u256_cmp(const U256& a, const U256& b) {
    for (int i = 3; i >= 0; i--) {
        if (a.w[i] < b.w[i]) return -1;
        if (a.w[i] > b.w[i]) return 1;
    }
    return 0;
}

// r = a + b, returns carry
static inline uint64_t u256_add(U256& r, const U256& a, const U256& b) {
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)a.w[i] + b.w[i];
        r.w[i] = (uint64_t)c;
        c >>= 64;
    }
    return (uint64_t)c;
}

// r = a - b, returns borrow
static inline uint64_t u256_sub(U256& r, const U256& a, const U256& b) {
    u128 br = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a.w[i] - b.w[i] - br;
        r.w[i] = (uint64_t)d;
        br = (d >> 64) ? 1 : 0;
    }
    return (uint64_t)br;
}

// ---------------------------------------------------------------------------
// Montgomery field/scalar context
// ---------------------------------------------------------------------------

struct Mont {
    U256 m;      // odd modulus
    uint64_t n0; // -m^{-1} mod 2^64
    U256 rr;     // R^2 mod m  (R = 2^256)
    U256 one;    // R mod m
};

static void mont_init(Mont& M, const U256& m) {
    M.m = m;
    // n0 = -m[0]^{-1} mod 2^64 via Newton iteration
    uint64_t x = m.w[0];  // correct to 3 bits (odd m)
    for (int i = 0; i < 6; i++) x *= 2 - m.w[0] * x;
    M.n0 = (uint64_t)(0 - x);
    // one = 2^256 mod m, rr = 2^512 mod m, by 512 modular doublings of 1
    U256 t = {{1, 0, 0, 0}};
    for (int i = 0; i < 512; i++) {
        uint64_t carry = u256_add(t, t, t);
        if (carry || u256_cmp(t, m) >= 0) u256_sub(t, t, m);
        if (i == 255) M.one = t;
    }
    M.rr = t;
}

// r = a*b*R^{-1} mod m (CIOS)
static U256 mont_mul(const Mont& M, const U256& a, const U256& b) {
    uint64_t t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        uint64_t carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)a.w[i] * b.w[j] + t[j] + carry;
            t[j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        u128 cur = (u128)t[4] + carry;
        t[4] = (uint64_t)cur;
        t[5] = (uint64_t)(cur >> 64);

        uint64_t mfac = t[0] * M.n0;
        cur = (u128)mfac * M.m.w[0] + t[0];
        carry = (uint64_t)(cur >> 64);
        for (int j = 1; j < 4; j++) {
            cur = (u128)mfac * M.m.w[j] + t[j] + carry;
            t[j - 1] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        cur = (u128)t[4] + carry;
        t[3] = (uint64_t)cur;
        t[4] = t[5] + (uint64_t)(cur >> 64);
    }
    U256 r = {{t[0], t[1], t[2], t[3]}};
    if (t[4] || u256_cmp(r, M.m) >= 0) u256_sub(r, r, M.m);
    return r;
}

static inline U256 mont_sqr(const Mont& M, const U256& a) {
    return mont_mul(M, a, a);
}

static inline U256 mont_to(const Mont& M, const U256& a) {
    return mont_mul(M, a, M.rr);
}

static inline U256 mont_from(const Mont& M, const U256& a) {
    static const U256 one = {{1, 0, 0, 0}};
    return mont_mul(M, a, one);
}

static inline U256 mod_add(const Mont& M, const U256& a, const U256& b) {
    U256 r;
    uint64_t carry = u256_add(r, a, b);
    if (carry || u256_cmp(r, M.m) >= 0) u256_sub(r, r, M.m);
    return r;
}

static inline U256 mod_sub(const Mont& M, const U256& a, const U256& b) {
    U256 r;
    if (u256_sub(r, a, b)) u256_add(r, r, M.m);
    return r;
}

// a^e mod m, all in Montgomery domain (e is a plain integer)
static U256 mont_pow(const Mont& M, const U256& a, const U256& e) {
    U256 r = M.one;
    U256 base = a;
    for (int i = 0; i < 256; i++) {
        if ((e.w[i / 64] >> (i % 64)) & 1) r = mont_mul(M, r, base);
        base = mont_sqr(M, base);
    }
    return r;
}

// a^{-1} mod m via Fermat (m prime), Montgomery domain in and out
static U256 mont_inv(const Mont& M, const U256& a) {
    U256 e = M.m;
    static const U256 two = {{2, 0, 0, 0}};
    u256_sub(e, e, two);
    return mont_pow(M, a, e);
}

// a mod m for a < 2^256 (one conditional subtract is NOT enough in general,
// but every caller passes a < 2m or reduces a hash: both curves' p and n have
// 2^256 - m < m, so a - m < m after at most one subtraction... except that is
// only true when a < 2m; for a raw 256-bit hash with m close to 2^256 one
// subtraction suffices. Loop to stay safe.)
static U256 u256_mod(const U256& a, const U256& m) {
    U256 r = a;
    while (u256_cmp(r, m) >= 0) u256_sub(r, r, m);
    return r;
}

// ---------------------------------------------------------------------------
// Curve context: Jacobian point ops in the Montgomery domain
// ---------------------------------------------------------------------------

struct Pt {
    U256 X, Y, Z;  // Jacobian, Montgomery domain; Z==0 => infinity
};

struct CurveCtx {
    Mont fp;       // field mod p
    Mont fn;       // scalars mod n
    U256 a, b;     // curve coefficients, Montgomery domain
    bool a_zero;
    Pt G;          // generator
    U256 n;        // group order (plain)
    U256 n_half;   // floor(n/2) (plain)
    U256 p;        // field prime (plain)
    U256 sqrt_e;   // (p+1)/4 (plain) — both curves have p ≡ 3 (mod 4)
    Pt g_tab[16];  // window table for G: g_tab[i] = i*G (g_tab[0] = inf)
};

static inline bool pt_is_inf(const Pt& P) { return u256_is_zero(P.Z); }

static Pt pt_dbl(const CurveCtx& C, const Pt& P) {
    const Mont& F = C.fp;
    if (pt_is_inf(P) || u256_is_zero(P.Y)) return {U256_ZERO, U256_ZERO, U256_ZERO};
    U256 A = mont_sqr(F, P.X);
    U256 B = mont_sqr(F, P.Y);
    U256 Cc = mont_sqr(F, B);
    // D = 2*((X+B)^2 - A - C)
    U256 t = mod_add(F, P.X, B);
    t = mont_sqr(F, t);
    t = mod_sub(F, t, A);
    t = mod_sub(F, t, Cc);
    U256 D = mod_add(F, t, t);
    // E = 3A + a*Z^4
    U256 E = mod_add(F, mod_add(F, A, A), A);
    if (!C.a_zero) {
        U256 z2 = mont_sqr(F, P.Z);
        U256 z4 = mont_sqr(F, z2);
        E = mod_add(F, E, mont_mul(F, C.a, z4));
    }
    U256 Fv = mont_sqr(F, E);
    Fv = mod_sub(F, Fv, D);
    Fv = mod_sub(F, Fv, D);
    Pt R;
    R.X = Fv;
    // Y3 = E*(D - F) - 8C
    U256 y = mont_mul(F, E, mod_sub(F, D, Fv));
    U256 c8 = mod_add(F, Cc, Cc);
    c8 = mod_add(F, c8, c8);
    c8 = mod_add(F, c8, c8);
    R.Y = mod_sub(F, y, c8);
    // Z3 = 2*Y*Z
    U256 yz = mont_mul(F, P.Y, P.Z);
    R.Z = mod_add(F, yz, yz);
    return R;
}

static Pt pt_add(const CurveCtx& C, const Pt& P, const Pt& Q) {
    const Mont& F = C.fp;
    if (pt_is_inf(P)) return Q;
    if (pt_is_inf(Q)) return P;
    U256 Z1Z1 = mont_sqr(F, P.Z);
    U256 Z2Z2 = mont_sqr(F, Q.Z);
    U256 U1 = mont_mul(F, P.X, Z2Z2);
    U256 U2 = mont_mul(F, Q.X, Z1Z1);
    U256 S1 = mont_mul(F, P.Y, mont_mul(F, Q.Z, Z2Z2));
    U256 S2 = mont_mul(F, Q.Y, mont_mul(F, P.Z, Z1Z1));
    if (u256_eq(U1, U2)) {
        if (!u256_eq(S1, S2)) return {U256_ZERO, U256_ZERO, U256_ZERO};
        return pt_dbl(C, P);
    }
    U256 H = mod_sub(F, U2, U1);
    U256 I = mod_add(F, H, H);
    I = mont_sqr(F, I);
    U256 J = mont_mul(F, H, I);
    U256 rr = mod_sub(F, S2, S1);
    rr = mod_add(F, rr, rr);
    U256 V = mont_mul(F, U1, I);
    Pt R;
    R.X = mod_sub(F, mod_sub(F, mod_sub(F, mont_sqr(F, rr), J), V), V);
    U256 t = mont_mul(F, rr, mod_sub(F, V, R.X));
    U256 s1j = mont_mul(F, S1, J);
    s1j = mod_add(F, s1j, s1j);
    R.Y = mod_sub(F, t, s1j);
    U256 z = mod_add(F, P.Z, Q.Z);
    z = mont_sqr(F, z);
    z = mod_sub(F, z, Z1Z1);
    z = mod_sub(F, z, Z2Z2);
    R.Z = mont_mul(F, z, H);
    return R;
}

// (x, y) affine, Montgomery domain; false when P is infinity
static bool pt_to_affine(const CurveCtx& C, const Pt& P, U256& x, U256& y) {
    if (pt_is_inf(P)) return false;
    const Mont& F = C.fp;
    U256 zi = mont_inv(F, P.Z);
    U256 zi2 = mont_sqr(F, zi);
    x = mont_mul(F, P.X, zi2);
    y = mont_mul(F, P.Y, mont_mul(F, zi2, zi));
    return true;
}

// y^2 == x^3 + a x + b, affine Montgomery domain
static bool on_curve_aff(const CurveCtx& C, const U256& x, const U256& y) {
    const Mont& F = C.fp;
    U256 lhs = mont_sqr(F, y);
    U256 rhs = mont_mul(F, mont_sqr(F, x), x);
    if (!C.a_zero) rhs = mod_add(F, rhs, mont_mul(F, C.a, x));
    rhs = mod_add(F, rhs, C.b);
    return u256_eq(lhs, rhs);
}

static void build_tab(const CurveCtx& C, const Pt& P, Pt tab[16]) {
    tab[0] = {U256_ZERO, U256_ZERO, U256_ZERO};
    tab[1] = P;
    for (int i = 2; i < 16; i++)
        tab[i] = (i & 1) ? pt_add(C, tab[i - 1], P) : pt_dbl(C, tab[i / 2]);
}

// k*P with a 4-bit fixed window over a prebuilt table
static Pt pt_mul_tab(const CurveCtx& C, const U256& k, const Pt tab[16]) {
    Pt R = {U256_ZERO, U256_ZERO, U256_ZERO};
    for (int w = 63; w >= 0; w--) {
        if (!pt_is_inf(R)) {
            R = pt_dbl(C, R);
            R = pt_dbl(C, R);
            R = pt_dbl(C, R);
            R = pt_dbl(C, R);
        }
        unsigned d = (k.w[w / 16] >> (4 * (w % 16))) & 0xf;
        if (d) R = pt_add(C, R, tab[d]);
    }
    return R;
}

// u1*G + u2*Q, Strauss–Shamir interleave with 4-bit windows
static Pt pt_shamir(const CurveCtx& C, const U256& u1, const U256& u2,
                    const Pt& Q) {
    Pt qtab[16];
    build_tab(C, Q, qtab);
    Pt R = {U256_ZERO, U256_ZERO, U256_ZERO};
    for (int w = 63; w >= 0; w--) {
        if (!pt_is_inf(R)) {
            R = pt_dbl(C, R);
            R = pt_dbl(C, R);
            R = pt_dbl(C, R);
            R = pt_dbl(C, R);
        }
        unsigned d1 = (u1.w[w / 16] >> (4 * (w % 16))) & 0xf;
        unsigned d2 = (u2.w[w / 16] >> (4 * (w % 16))) & 0xf;
        if (d1) R = pt_add(C, R, C.g_tab[d1]);
        if (d2) R = pt_add(C, R, qtab[d2]);
    }
    return R;
}

// ---------------------------------------------------------------------------
// The two curves (parameters match crypto/ref/ecdsa.py:37-55)
// ---------------------------------------------------------------------------

static void curve_init(CurveCtx& C, const uint8_t p_be[32], const uint8_t a_be[32],
                       const uint8_t b_be[32], const uint8_t gx_be[32],
                       const uint8_t gy_be[32], const uint8_t n_be[32]) {
    C.p = u256_load_be(p_be);
    C.n = u256_load_be(n_be);
    mont_init(C.fp, C.p);
    mont_init(C.fn, C.n);
    U256 a_plain = u256_load_be(a_be);
    C.a_zero = u256_is_zero(a_plain);
    C.a = mont_to(C.fp, a_plain);
    C.b = mont_to(C.fp, u256_load_be(b_be));
    C.G.X = mont_to(C.fp, u256_load_be(gx_be));
    C.G.Y = mont_to(C.fp, u256_load_be(gy_be));
    C.G.Z = C.fp.one;
    // n_half = n >> 1
    for (int i = 0; i < 4; i++)
        C.n_half.w[i] = (C.n.w[i] >> 1) | (i < 3 ? (C.n.w[i + 1] << 63) : 0);
    // sqrt exponent (p+1)/4
    U256 p1;
    static const U256 one_c = {{1, 0, 0, 0}};
    u256_add(p1, C.p, one_c);  // no overflow: p < 2^256 - 1 for both curves
    for (int i = 0; i < 4; i++)
        C.sqrt_e.w[i] = (p1.w[i] >> 2) | (i < 3 ? (p1.w[i + 1] << 62) : 0);
    build_tab(C, C.G, C.g_tab);
}

static const uint8_t SECP_P[32] = {
    0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
    0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xfe,0xff,0xff,0xfc,0x2f};
static const uint8_t SECP_A[32] = {0};
static const uint8_t SECP_B[32] = {
    0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0x07};
static const uint8_t SECP_GX[32] = {
    0x79,0xbe,0x66,0x7e,0xf9,0xdc,0xbb,0xac,0x55,0xa0,0x62,0x95,0xce,0x87,0x0b,0x07,
    0x02,0x9b,0xfc,0xdb,0x2d,0xce,0x28,0xd9,0x59,0xf2,0x81,0x5b,0x16,0xf8,0x17,0x98};
static const uint8_t SECP_GY[32] = {
    0x48,0x3a,0xda,0x77,0x26,0xa3,0xc4,0x65,0x5d,0xa4,0xfb,0xfc,0x0e,0x11,0x08,0xa8,
    0xfd,0x17,0xb4,0x48,0xa6,0x85,0x54,0x19,0x9c,0x47,0xd0,0x8f,0xfb,0x10,0xd4,0xb8};
static const uint8_t SECP_N[32] = {
    0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xfe,
    0xba,0xae,0xdc,0xe6,0xaf,0x48,0xa0,0x3b,0xbf,0xd2,0x5e,0x8c,0xd0,0x36,0x41,0x41};

static const uint8_t SM2_P[32] = {
    0xff,0xff,0xff,0xfe,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
    0xff,0xff,0xff,0xff,0x00,0x00,0x00,0x00,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff};
static const uint8_t SM2_A[32] = {
    0xff,0xff,0xff,0xfe,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
    0xff,0xff,0xff,0xff,0x00,0x00,0x00,0x00,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xfc};
static const uint8_t SM2_B[32] = {
    0x28,0xe9,0xfa,0x9e,0x9d,0x9f,0x5e,0x34,0x4d,0x5a,0x9e,0x4b,0xcf,0x65,0x09,0xa7,
    0xf3,0x97,0x89,0xf5,0x15,0xab,0x8f,0x92,0xdd,0xbc,0xbd,0x41,0x4d,0x94,0x0e,0x93};
static const uint8_t SM2_GX[32] = {
    0x32,0xc4,0xae,0x2c,0x1f,0x19,0x81,0x19,0x5f,0x99,0x04,0x46,0x6a,0x39,0xc9,0x94,
    0x8f,0xe3,0x0b,0xbf,0xf2,0x66,0x0b,0xe1,0x71,0x5a,0x45,0x89,0x33,0x4c,0x74,0xc7};
static const uint8_t SM2_GY[32] = {
    0xbc,0x37,0x36,0xa2,0xf4,0xf6,0x77,0x9c,0x59,0xbd,0xce,0xe3,0x6b,0x69,0x21,0x53,
    0xd0,0xa9,0x87,0x7c,0xc6,0x2a,0x47,0x40,0x02,0xdf,0x32,0xe5,0x21,0x39,0xf0,0xa0};
static const uint8_t SM2_N[32] = {
    0xff,0xff,0xff,0xfe,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
    0x72,0x03,0xdf,0x6b,0x21,0xc6,0x05,0x2b,0x53,0xbb,0xf4,0x09,0x39,0xd5,0x41,0x23};

static const CurveCtx& secp_ctx() {
    static const CurveCtx C = [] {
        CurveCtx c;
        curve_init(c, SECP_P, SECP_A, SECP_B, SECP_GX, SECP_GY, SECP_N);
        return c;
    }();
    return C;
}

static const CurveCtx& sm2_ctx() {
    static const CurveCtx C = [] {
        CurveCtx c;
        curve_init(c, SM2_P, SM2_A, SM2_B, SM2_GX, SM2_GY, SM2_N);
        return c;
    }();
    return C;
}

// ---------------------------------------------------------------------------
// HMAC-SHA256 + RFC 6979 deterministic nonce
// (bit-identical to crypto/ref/ecdsa.py:_rfc6979_k, incl. the retry octets)
// ---------------------------------------------------------------------------

static void hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* d1,
                        size_t l1, const uint8_t* d2, size_t l2,
                        const uint8_t* d3, size_t l3, uint8_t out[32]) {
    uint8_t k[64];
    std::memset(k, 0, 64);
    if (keylen > 64) {
        fisco_sha256(key, keylen, k);
    } else {
        std::memcpy(k, key, keylen);
    }
    uint8_t buf[64 + 32 + 1 + 32 + 36];  // ipad + V + tag + x + h1(+retry)
    for (int i = 0; i < 64; i++) buf[i] = k[i] ^ 0x36;
    size_t off = 64;
    std::memcpy(buf + off, d1, l1); off += l1;
    if (l2) { std::memcpy(buf + off, d2, l2); off += l2; }
    if (l3) { std::memcpy(buf + off, d3, l3); off += l3; }
    uint8_t inner[32];
    fisco_sha256(buf, off, inner);
    uint8_t obuf[64 + 32];
    for (int i = 0; i < 64; i++) obuf[i] = k[i] ^ 0x5c;
    std::memcpy(obuf + 64, inner, 32);
    fisco_sha256(obuf, 96, out);
}

// k = RFC6979(d, z mod n, retry) in [1, n)
static U256 rfc6979_k(const CurveCtx& C, const U256& d, const U256& z,
                      uint32_t retry) {
    uint8_t x[32], h1[36];
    u256_store_be(d, x);
    U256 zr = u256_mod(z, C.n);
    u256_store_be(zr, h1);
    size_t h1len = 32;
    if (retry) {
        h1[32] = uint8_t(retry >> 24);
        h1[33] = uint8_t(retry >> 16);
        h1[34] = uint8_t(retry >> 8);
        h1[35] = uint8_t(retry);
        h1len = 36;
    }
    uint8_t V[32], K[32];
    std::memset(V, 0x01, 32);
    std::memset(K, 0x00, 32);
    static const uint8_t T0 = 0x00, T1 = 0x01;
    uint8_t vx[1 + 32 + 36];
    // K = HMAC(K, V || 0x00 || x || h1)
    vx[0] = T0;
    std::memcpy(vx + 1, x, 32);
    std::memcpy(vx + 33, h1, h1len);
    hmac_sha256(K, 32, V, 32, vx, 1 + 32 + h1len, nullptr, 0, K);
    hmac_sha256(K, 32, V, 32, nullptr, 0, nullptr, 0, V);
    vx[0] = T1;
    std::memcpy(vx + 1, x, 32);
    std::memcpy(vx + 33, h1, h1len);
    hmac_sha256(K, 32, V, 32, vx, 1 + 32 + h1len, nullptr, 0, K);
    hmac_sha256(K, 32, V, 32, nullptr, 0, nullptr, 0, V);
    for (;;) {
        hmac_sha256(K, 32, V, 32, nullptr, 0, nullptr, 0, V);
        U256 cand = u256_load_be(V);
        if (!u256_is_zero(cand) && u256_cmp(cand, C.n) < 0) return cand;
        hmac_sha256(K, 32, V, 32, &T0, 1, nullptr, 0, K);
        hmac_sha256(K, 32, V, 32, nullptr, 0, nullptr, 0, V);
    }
}

// parse an uncompressed pubkey into an affine Montgomery point; false when
// off-curve
static bool parse_pub(const CurveCtx& C, const uint8_t pub[64], U256& x,
                      U256& y) {
    U256 xp = u256_load_be(pub);
    U256 yp = u256_load_be(pub + 32);
    if (u256_cmp(xp, C.p) >= 0 || u256_cmp(yp, C.p) >= 0) return false;
    x = mont_to(C.fp, xp);
    y = mont_to(C.fp, yp);
    return on_curve_aff(C, x, y);
}

}  // namespace

// ---------------------------------------------------------------------------
// exported EC API — scalars are 32-byte big-endian; pubkeys 64-byte x‖y
// ---------------------------------------------------------------------------

// returns 1 when the signature verifies (semantics: crypto/ref/ecdsa.py:157)
int fisco_secp256k1_verify(const uint8_t z32[32], const uint8_t r32[32],
                           const uint8_t s32[32], const uint8_t pub[64]) {
    const CurveCtx& C = secp_ctx();
    U256 r = u256_load_be(r32), s = u256_load_be(s32);
    if (u256_is_zero(r) || u256_is_zero(s)) return 0;
    if (u256_cmp(r, C.n) >= 0 || u256_cmp(s, C.n) >= 0) return 0;
    U256 qx, qy;
    if (!parse_pub(C, pub, qx, qy)) return 0;
    U256 z = u256_mod(u256_load_be(z32), C.n);
    const Mont& N = C.fn;
    U256 w = mont_inv(N, mont_to(N, s));
    U256 u1 = mont_from(N, mont_mul(N, mont_to(N, z), w));
    U256 u2 = mont_from(N, mont_mul(N, mont_to(N, r), w));
    Pt Q = {qx, qy, C.fp.one};
    Pt R = pt_shamir(C, u1, u2, Q);
    U256 rx, ry;
    if (!pt_to_affine(C, R, rx, ry)) return 0;
    U256 rxp = u256_mod(mont_from(C.fp, rx), C.n);
    return u256_eq(rxp, u256_mod(r, C.n)) ? 1 : 0;
}

// recover the 64-byte pubkey; v in {0..3} or {27, 28}; returns 1 on success
// (semantics: crypto/ref/ecdsa.py:172)
int fisco_secp256k1_recover(const uint8_t z32[32], const uint8_t r32[32],
                            const uint8_t s32[32], int v, uint8_t pub_out[64]) {
    const CurveCtx& C = secp_ctx();
    if (v >= 27) v -= 27;
    if (v < 0 || v > 3) return 0;
    U256 r = u256_load_be(r32), s = u256_load_be(s32);
    if (u256_is_zero(r) || u256_is_zero(s)) return 0;
    if (u256_cmp(r, C.n) >= 0 || u256_cmp(s, C.n) >= 0) return 0;
    U256 x = r;
    if (v & 2) {
        if (u256_add(x, x, C.n)) return 0;  // overflowed 2^256 => >= p
    }
    if (u256_cmp(x, C.p) >= 0) return 0;
    const Mont& F = C.fp;
    U256 xm = mont_to(F, x);
    U256 ysq = mont_mul(F, mont_sqr(F, xm), xm);
    if (!C.a_zero) ysq = mod_add(F, ysq, mont_mul(F, C.a, xm));
    ysq = mod_add(F, ysq, C.b);
    U256 ym = mont_pow(F, ysq, C.sqrt_e);
    if (!u256_eq(mont_sqr(F, ym), ysq)) return 0;  // non-residue
    U256 y_plain = mont_from(F, ym);
    if ((y_plain.w[0] & 1) != (unsigned)(v & 1)) {
        u256_sub(y_plain, C.p, y_plain);
        ym = mont_to(F, y_plain);
    }
    // Q = r^{-1} (s·R − z·G)
    U256 z = u256_mod(u256_load_be(z32), C.n);
    const Mont& N = C.fn;
    U256 rinv = mont_inv(N, mont_to(N, r));
    U256 u1 = mont_from(N, mont_mul(N, mont_to(N, s), rinv));       // s/r
    U256 zneg = u256_is_zero(z) ? z : ([&] { U256 t; u256_sub(t, C.n, z); return t; })();
    U256 u2 = mont_from(N, mont_mul(N, mont_to(N, zneg), rinv));    // -z/r
    Pt Rpt = {xm, ym, F.one};
    // shamir computes u_G·G + u_Q·Q: here G-scalar is u2(-z/r), Q=R with u1
    Pt Q = pt_shamir(C, u2, u1, Rpt);
    U256 qx, qy;
    if (!pt_to_affine(C, Q, qx, qy)) return 0;
    if (!on_curve_aff(C, qx, qy)) return 0;
    u256_store_be(mont_from(F, qx), pub_out);
    u256_store_be(mont_from(F, qy), pub_out + 32);
    return 1;
}

// deterministic low-s signature; *v_out in {0..3}; returns 1 on success
// (semantics + nonce derivation: crypto/ref/ecdsa.py:131-154)
int fisco_secp256k1_sign(const uint8_t z32[32], const uint8_t d32[32],
                         uint8_t r_out[32], uint8_t s_out[32], int* v_out) {
    const CurveCtx& C = secp_ctx();
    U256 d = u256_load_be(d32);
    if (u256_is_zero(d) || u256_cmp(d, C.n) >= 0) return 0;
    U256 z = u256_load_be(z32);
    const Mont& N = C.fn;
    U256 zm = mont_to(N, u256_mod(z, C.n));
    U256 dm = mont_to(N, d);
    for (uint32_t retry = 0; retry < 64; retry++) {
        U256 k = rfc6979_k(C, d, z, retry);
        Pt R = pt_mul_tab(C, k, C.g_tab);
        U256 rx, ry;
        if (!pt_to_affine(C, R, rx, ry)) continue;
        U256 rx_plain = mont_from(C.fp, rx);
        U256 r = u256_mod(rx_plain, C.n);
        if (u256_is_zero(r)) continue;
        // s = k^{-1} (z + r d) mod n
        U256 kinv = mont_inv(N, mont_to(N, k));
        U256 rd = mont_mul(N, mont_to(N, r), dm);
        U256 s = mont_from(N, mont_mul(N, mod_add(N, zm, rd), kinv));
        if (u256_is_zero(s)) continue;
        U256 ry_plain = mont_from(C.fp, ry);
        int v = int(ry_plain.w[0] & 1) | (u256_cmp(rx_plain, C.n) >= 0 ? 2 : 0);
        if (u256_cmp(s, C.n_half) > 0) {
            u256_sub(s, C.n, s);
            v ^= 1;
        }
        u256_store_be(r, r_out);
        u256_store_be(s, s_out);
        *v_out = v;
        return 1;
    }
    return 0;
}

// SM2 verify; e32 = SM3(ZA ‖ M) computed by the caller
// (semantics: crypto/ref/ecdsa.py:247-260)
int fisco_sm2_verify(const uint8_t e32[32], const uint8_t r32[32],
                     const uint8_t s32[32], const uint8_t pub[64]) {
    const CurveCtx& C = sm2_ctx();
    U256 r = u256_load_be(r32), s = u256_load_be(s32);
    if (u256_is_zero(r) || u256_is_zero(s)) return 0;
    if (u256_cmp(r, C.n) >= 0 || u256_cmp(s, C.n) >= 0) return 0;
    U256 qx, qy;
    if (!parse_pub(C, pub, qx, qy)) return 0;
    // t = (r + s) mod n, t != 0
    U256 t;
    uint64_t carry = u256_add(t, r, s);
    if (carry || u256_cmp(t, C.n) >= 0) u256_sub(t, t, C.n);
    if (u256_is_zero(t)) return 0;
    Pt Q = {qx, qy, C.fp.one};
    Pt P1 = pt_shamir(C, s, t, Q);
    U256 x1, y1;
    if (!pt_to_affine(C, P1, x1, y1)) return 0;
    // (e + x1) mod n == r
    U256 e = u256_mod(u256_load_be(e32), C.n);
    U256 x1p = u256_mod(mont_from(C.fp, x1), C.n);
    U256 lhs;
    carry = u256_add(lhs, e, x1p);
    if (carry || u256_cmp(lhs, C.n) >= 0) u256_sub(lhs, lhs, C.n);
    return u256_eq(lhs, r) ? 1 : 0;
}

// SM2 deterministic sign; e32 = SM3(ZA ‖ M) computed by the caller
// (semantics + nonce derivation: crypto/ref/ecdsa.py:229-244)
int fisco_sm2_sign(const uint8_t e32[32], const uint8_t d32[32],
                   uint8_t r_out[32], uint8_t s_out[32]) {
    const CurveCtx& C = sm2_ctx();
    U256 d = u256_load_be(d32);
    if (u256_is_zero(d) || u256_cmp(d, C.n) >= 0) return 0;
    U256 e_raw = u256_load_be(e32);
    U256 e = u256_mod(e_raw, C.n);
    const Mont& N = C.fn;
    U256 dm = mont_to(N, d);
    // (1 + d)^{-1} mod n
    U256 dp1 = mod_add(N, dm, N.one);
    if (u256_is_zero(dp1)) return 0;
    U256 dp1_inv = mont_inv(N, dp1);
    for (uint32_t retry = 0; retry < 64; retry++) {
        U256 k = rfc6979_k(C, d, e_raw, retry);
        Pt P1 = pt_mul_tab(C, k, C.g_tab);
        U256 x1, y1;
        if (!pt_to_affine(C, P1, x1, y1)) continue;
        U256 x1p = u256_mod(mont_from(C.fp, x1), C.n);
        // r = (e + x1) mod n
        U256 r;
        uint64_t carry = u256_add(r, e, x1p);
        if (carry || u256_cmp(r, C.n) >= 0) u256_sub(r, r, C.n);
        if (u256_is_zero(r)) continue;
        // reject r + k == n
        U256 rk;
        if (!u256_add(rk, r, k) && u256_eq(rk, C.n)) continue;
        // s = (1+d)^{-1} (k - r d) mod n
        U256 krd = mod_sub(N, mont_to(N, k), mont_mul(N, mont_to(N, r), dm));
        U256 s = mont_from(N, mont_mul(N, krd, dp1_inv));
        if (u256_is_zero(s)) continue;
        u256_store_be(r, r_out);
        u256_store_be(s, s_out);
        return 1;
    }
    return 0;
}

// d*G for either curve (0 = secp256k1, 1 = sm2); returns 1 on success
int fisco_ec_pubkey(int curve, const uint8_t d32[32], uint8_t pub_out[64]) {
    const CurveCtx& C = curve ? sm2_ctx() : secp_ctx();
    U256 d = u256_load_be(d32);
    U256 dmod = u256_mod(d, C.n);
    if (u256_is_zero(dmod)) return 0;
    Pt P = pt_mul_tab(C, dmod, C.g_tab);
    U256 x, y;
    if (!pt_to_affine(C, P, x, y)) return 0;
    u256_store_be(mont_from(C.fp, x), pub_out);
    u256_store_be(mont_from(C.fp, y), pub_out + 32);
    return 1;
}

// ===========================================================================
// Ed25519 (RFC 8032) — the third signature suite's single-item host path.
// Reference: bcos-crypto/signature/ed25519/Ed25519Crypto.cpp (wedpr FFI).
// Bit-identical to fisco_bcos_tpu/crypto/ref/ed25519.py: extended twisted-
// Edwards coordinates, cofactored verification 8SB == 8R + 8kA, the RFC
// 8032 §5.1.7 s < L malleability guard.
// ===========================================================================

namespace {

// ---- SHA-512 (FIPS 180-4) -------------------------------------------------

static const uint64_t SHA512_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};

static inline uint64_t ror64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

static void sha512_block(uint64_t h[8], const uint8_t* p) {
    uint64_t w[80];
    for (int i = 0; i < 16; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[8 * i + j];
        w[i] = v;
    }
    for (int i = 16; i < 80; i++) {
        uint64_t s0 = ror64(w[i - 15], 1) ^ ror64(w[i - 15], 8) ^ (w[i - 15] >> 7);
        uint64_t s1 = ror64(w[i - 2], 19) ^ ror64(w[i - 2], 61) ^ (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint64_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
        uint64_t S1 = ror64(e, 14) ^ ror64(e, 18) ^ ror64(e, 41);
        uint64_t ch = (e & f) ^ ((~e) & g);
        uint64_t t1 = hh + S1 + ch + SHA512_K[i] + w[i];
        uint64_t S0 = ror64(a, 28) ^ ror64(a, 34) ^ ror64(a, 39);
        uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = S0 + maj;
        hh = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

static void sha512(const uint8_t* data, size_t len, uint8_t out[64]) {
    uint64_t h[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
        0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
    };
    size_t full = len / 128;
    for (size_t i = 0; i < full; i++) sha512_block(h, data + 128 * i);
    uint8_t tail[256];
    size_t rem = len - 128 * full;
    std::memcpy(tail, data + 128 * full, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 17 <= 128) ? 128 : 256;
    std::memset(tail + rem + 1, 0, tail_len - rem - 1);
    uint64_t bits = uint64_t(len) * 8;  // messages < 2^61 bytes
    for (int i = 0; i < 8; i++)
        tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
    sha512_block(h, tail);
    if (tail_len == 256) sha512_block(h, tail + 128);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = uint8_t(h[i] >> (8 * (7 - j)));
}

// ---- edwards25519 ---------------------------------------------------------

struct EdPt {
    U256 X, Y, Z, T;  // extended coordinates, Montgomery domain
};

struct EdCtx {
    Mont fp;        // mod P = 2^255 - 19
    Mont fl;        // mod L (group order)
    U256 P, L;      // plain
    U256 d;         // curve d, Montgomery domain
    U256 sqrt_m1;   // 2^((P-1)/4), Montgomery domain
    U256 exp_x;     // (P+3)/8, plain exponent
    U256 bx, by;    // base point affine, Montgomery domain
    EdPt B;         // base point, extended
    EdPt b_tab[16]; // 4-bit window table for B (b_tab[0] = identity)
};

static EdPt ed_identity(const EdCtx& C);
static EdPt ed_add(const EdCtx& C, const EdPt& p, const EdPt& q);
static void ed_build_tab(const EdCtx& C, const EdPt& p, EdPt tab[16]);

static const EdCtx& ed_ctx() {
    static const EdCtx C = [] {
        EdCtx c;
        // P = 2^255 - 19
        c.P = {{0xffffffffffffffedULL, 0xffffffffffffffffULL,
                0xffffffffffffffffULL, 0x7fffffffffffffffULL}};
        // L = 2^252 + 27742317777372353535851937790883648493
        c.L = {{0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                0x0000000000000000ULL, 0x1000000000000000ULL}};
        mont_init(c.fp, c.P);
        mont_init(c.fl, c.L);
        // d = -121665/121666 mod P
        U256 n121665 = {{121665, 0, 0, 0}};
        U256 n121666 = {{121666, 0, 0, 0}};
        U256 inv = mont_inv(c.fp, mont_to(c.fp, n121666));
        U256 dm = mont_mul(c.fp, mont_to(c.fp, n121665), inv);
        U256 zero = U256_ZERO;
        c.d = mod_sub(c.fp, zero, dm);  // negate
        // exponents: (P+3)/8 and sqrt(-1) = 2^((P-1)/4)
        U256 p3;
        static const U256 three = {{3, 0, 0, 0}};
        u256_add(p3, c.P, three);  // no overflow (P < 2^255)
        for (int i = 0; i < 4; i++)
            c.exp_x.w[i] = (p3.w[i] >> 3) | (i < 3 ? (p3.w[i + 1] << 61) : 0);
        U256 p1;
        static const U256 one_c = {{1, 0, 0, 0}};
        u256_sub(p1, c.P, one_c);
        U256 e4;
        for (int i = 0; i < 4; i++)
            e4.w[i] = (p1.w[i] >> 2) | (i < 3 ? (p1.w[i + 1] << 62) : 0);
        U256 two = {{2, 0, 0, 0}};
        c.sqrt_m1 = mont_pow(c.fp, mont_to(c.fp, two), e4);
        // base point: y = 4/5, x recovered with sign 0
        U256 four = {{4, 0, 0, 0}};
        U256 five = {{5, 0, 0, 0}};
        c.by = mont_mul(
            c.fp, mont_to(c.fp, four), mont_inv(c.fp, mont_to(c.fp, five)));
        // x^2 = (y^2 - 1) / (d y^2 + 1)
        U256 y2 = mont_sqr(c.fp, c.by);
        U256 onem = c.fp.one;
        U256 num = mod_sub(c.fp, y2, onem);
        U256 den = mod_add(c.fp, mont_mul(c.fp, c.d, y2), onem);
        U256 x2 = mont_mul(c.fp, num, mont_inv(c.fp, den));
        U256 x = mont_pow(c.fp, x2, c.exp_x);
        if (!u256_eq(mont_sqr(c.fp, x), x2))
            x = mont_mul(c.fp, x, c.sqrt_m1);
        U256 xp = mont_from(c.fp, x);
        if (xp.w[0] & 1) {  // base x has sign 0
            u256_sub(xp, c.P, xp);
            x = mont_to(c.fp, xp);
        }
        c.bx = x;
        c.B = {c.bx, c.by, c.fp.one, mont_mul(c.fp, c.bx, c.by)};
        ed_build_tab(c, c.B, c.b_tab);
        return c;
    }();
    return C;
}

static EdPt ed_identity(const EdCtx& C) {
    return {U256_ZERO, C.fp.one, C.fp.one, U256_ZERO};
}

// unified extended addition (matches crypto/ref/ed25519.py:_add)
static EdPt ed_add(const EdCtx& C, const EdPt& p, const EdPt& q) {
    const Mont& F = C.fp;
    U256 a = mont_mul(F, mod_sub(F, p.Y, p.X), mod_sub(F, q.Y, q.X));
    U256 b = mont_mul(F, mod_add(F, p.Y, p.X), mod_add(F, q.Y, q.X));
    U256 t2 = mont_mul(F, p.T, q.T);
    U256 cc = mont_mul(F, mod_add(F, t2, t2), C.d);
    U256 zz = mont_mul(F, p.Z, q.Z);
    U256 dd = mod_add(F, zz, zz);
    U256 e = mod_sub(F, b, a);
    U256 f = mod_sub(F, dd, cc);
    U256 g = mod_add(F, dd, cc);
    U256 h = mod_add(F, b, a);
    return {
        mont_mul(F, e, f),
        mont_mul(F, g, h),
        mont_mul(F, f, g),
        mont_mul(F, e, h),
    };
}

static void ed_build_tab(const EdCtx& C, const EdPt& p, EdPt tab[16]) {
    tab[0] = ed_identity(C);
    tab[1] = p;
    for (int i = 2; i < 16; i++)
        tab[i] = (i & 1) ? ed_add(C, tab[i - 1], p)
                         : ed_add(C, tab[i / 2], tab[i / 2]);
}

// 4-bit fixed-window scalar mult over a prebuilt table (same shape as the
// Weierstrass pt_mul_tab; the unified Edwards add needs no special cases)
static EdPt ed_mul_tab(const EdCtx& C, const U256& s, const EdPt tab[16]) {
    EdPt q = ed_identity(C);
    bool started = false;
    for (int w = 63; w >= 0; w--) {
        if (started) {
            q = ed_add(C, q, q);
            q = ed_add(C, q, q);
            q = ed_add(C, q, q);
            q = ed_add(C, q, q);
        }
        unsigned dgt = (s.w[w / 16] >> (4 * (w % 16))) & 0xf;
        if (dgt) {
            q = ed_add(C, q, tab[dgt]);
            started = true;
        }
    }
    return q;
}

static EdPt ed_mul(const EdCtx& C, const U256& s, const EdPt& p) {
    EdPt tab[16];
    ed_build_tab(C, p, tab);
    return ed_mul_tab(C, s, tab);
}

// decompress 32 LE bytes -> point; false when off-curve/non-canonical
// (matches crypto/ref/ed25519.py:_recover_x/_decompress)
static bool ed_decompress(const EdCtx& C, const uint8_t in[32], EdPt& out) {
    uint8_t le[32];
    std::memcpy(le, in, 32);
    int sign = le[31] >> 7;
    le[31] &= 0x7f;
    // bytes are little-endian; u256_load_be wants big-endian
    uint8_t be[32];
    for (int i = 0; i < 32; i++) be[i] = le[31 - i];
    U256 y = u256_load_be(be);
    if (u256_cmp(y, C.P) >= 0) return false;
    const Mont& F = C.fp;
    U256 ym = mont_to(F, y);
    U256 y2 = mont_sqr(F, ym);
    U256 num = mod_sub(F, y2, F.one);
    U256 den = mod_add(F, mont_mul(F, C.d, y2), F.one);
    U256 x2 = mont_mul(F, num, mont_inv(F, den));
    if (u256_is_zero(x2)) {
        if (sign != 0) return false;
        out = {U256_ZERO, ym, F.one, U256_ZERO};
        return true;
    }
    U256 x = mont_pow(F, x2, C.exp_x);
    if (!u256_eq(mont_sqr(F, x), x2)) x = mont_mul(F, x, C.sqrt_m1);
    if (!u256_eq(mont_sqr(F, x), x2)) return false;
    U256 xp = mont_from(F, x);
    if ((int)(xp.w[0] & 1) != sign) {
        u256_sub(xp, C.P, xp);
        x = mont_to(F, xp);
    }
    out = {x, ym, F.one, mont_mul(F, x, ym)};
    return true;
}

static void ed_compress(const EdCtx& C, const EdPt& p, uint8_t out[32]) {
    const Mont& F = C.fp;
    U256 zi = mont_inv(F, p.Z);
    U256 x = mont_from(F, mont_mul(F, p.X, zi));
    U256 y = mont_from(F, mont_mul(F, p.Y, zi));
    uint8_t be[32];
    u256_store_be(y, be);
    for (int i = 0; i < 32; i++) out[i] = be[31 - i];
    out[31] |= uint8_t((x.w[0] & 1) << 7);
}

static bool ed_eq(const EdCtx& C, const EdPt& p, const EdPt& q) {
    const Mont& F = C.fp;
    // x1 z2 == x2 z1 and y1 z2 == y2 z1
    return u256_eq(mont_mul(F, p.X, q.Z), mont_mul(F, q.X, p.Z)) &&
           u256_eq(mont_mul(F, p.Y, q.Z), mont_mul(F, q.Y, p.Z));
}

// 64-byte little-endian hash -> scalar mod L
static U256 ed_scalar_from_hash64(const EdCtx& C, const uint8_t h[64]) {
    uint8_t be_lo[32], be_hi[32];
    for (int i = 0; i < 32; i++) be_lo[i] = h[31 - i];
    for (int i = 0; i < 32; i++) be_hi[i] = h[63 - i];
    U256 lo = u256_mod(u256_load_be(be_lo), C.L);
    U256 hi = u256_mod(u256_load_be(be_hi), C.L);
    // hi * 2^256 + lo  (mod L);  fl.one == 2^256 mod L
    const Mont& N = C.fl;
    U256 hi_shift = mont_from(
        N, mont_mul(N, mont_to(N, hi), mont_to(N, N.one)));
    U256 out;
    uint64_t carry = u256_add(out, hi_shift, lo);
    if (carry || u256_cmp(out, C.L) >= 0) u256_sub(out, out, C.L);
    return out;
}

// multiply a scalar (< L or < 2^253) by small m (8), plain domain, no mod
static U256 u256_small_mul(const U256& a, uint64_t m) {
    U256 r;
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 cur = (u128)a.w[i] * m + carry;
        r.w[i] = (uint64_t)cur;
        carry = cur >> 64;
    }
    return r;  // callers guarantee no 2^256 overflow (8L < 2^256)
}

}  // namespace

// verify a 64-byte R‖S signature over msg with a 32-byte compressed pubkey
// (semantics: crypto/ref/ed25519.py:126-140, cofactored)
int fisco_ed25519_verify(const uint8_t pub[32], const uint8_t* msg,
                         size_t msg_len, const uint8_t sig[64]) {
    const EdCtx& C = ed_ctx();
    EdPt A, R;
    if (!ed_decompress(C, pub, A) || !ed_decompress(C, sig, R)) return 0;
    uint8_t s_be[32];
    for (int i = 0; i < 32; i++) s_be[i] = sig[63 - i];
    U256 s = u256_load_be(s_be);
    if (u256_cmp(s, C.L) >= 0) return 0;  // malleability guard
    // k = SHA512(R ‖ pub ‖ msg) mod L
    uint8_t buf_stack[4096];
    uint8_t* buf = buf_stack;
    size_t total = 64 + msg_len;
    uint8_t* heap = nullptr;
    if (total > sizeof(buf_stack)) {
        heap = new uint8_t[total];
        buf = heap;
    }
    std::memcpy(buf, sig, 32);
    std::memcpy(buf + 32, pub, 32);
    if (msg_len) std::memcpy(buf + 64, msg, msg_len);
    uint8_t kh[64];
    sha512(buf, total, kh);
    delete[] heap;
    U256 k = ed_scalar_from_hash64(C, kh);
    // 8sB == 8R + (8k)A
    EdPt lhs = ed_mul_tab(C, u256_small_mul(s, 8), C.b_tab);
    EdPt r8 = R;
    for (int i = 0; i < 3; i++) r8 = ed_add(C, r8, r8);
    EdPt rhs = ed_add(C, r8, ed_mul(C, u256_small_mul(k, 8), A));
    return ed_eq(C, lhs, rhs) ? 1 : 0;
}

// seed -> 32-byte compressed pubkey (crypto/ref/ed25519.py:108-111)
int fisco_ed25519_pubkey(const uint8_t seed[32], uint8_t pub_out[32]) {
    const EdCtx& C = ed_ctx();
    uint8_t h[64];
    sha512(seed, 32, h);
    h[0] &= 0xf8;
    h[31] &= 0x7f;
    h[31] |= 0x40;
    uint8_t be[32];
    for (int i = 0; i < 32; i++) be[i] = h[31 - i];
    U256 a = u256_load_be(be);
    ed_compress(C, ed_mul_tab(C, a, C.b_tab), pub_out);
    return 1;
}

// deterministic RFC 8032 sign (crypto/ref/ed25519.py:114-123)
int fisco_ed25519_sign(const uint8_t seed[32], const uint8_t* msg,
                       size_t msg_len, uint8_t sig_out[64]) {
    const EdCtx& C = ed_ctx();
    uint8_t h[64];
    sha512(seed, 32, h);
    uint8_t a_bytes[32];
    std::memcpy(a_bytes, h, 32);
    a_bytes[0] &= 0xf8;
    a_bytes[31] &= 0x7f;
    a_bytes[31] |= 0x40;
    uint8_t be[32];
    for (int i = 0; i < 32; i++) be[i] = a_bytes[31 - i];
    U256 a = u256_load_be(be);
    uint8_t apub[32];
    ed_compress(C, ed_mul_tab(C, a, C.b_tab), apub);
    // r = SHA512(prefix ‖ msg) mod L
    size_t total = 32 + msg_len;
    uint8_t buf_stack[4096];
    uint8_t* buf = buf_stack;
    uint8_t* heap = nullptr;
    if (total + 32 > sizeof(buf_stack)) {  // reused below with 64-byte head
        heap = new uint8_t[total + 32];
        buf = heap;
    }
    std::memcpy(buf, h + 32, 32);
    if (msg_len) std::memcpy(buf + 32, msg, msg_len);
    uint8_t rh[64];
    sha512(buf, total, rh);
    U256 r = ed_scalar_from_hash64(C, rh);
    uint8_t rpt[32];
    ed_compress(C, ed_mul_tab(C, r, C.b_tab), rpt);
    // k = SHA512(R ‖ A ‖ msg) mod L
    std::memcpy(buf, rpt, 32);
    std::memcpy(buf + 32, apub, 32);
    if (msg_len) std::memcpy(buf + 64, msg, msg_len);
    uint8_t kh[64];
    sha512(buf, 64 + msg_len, kh);
    delete[] heap;
    U256 k = ed_scalar_from_hash64(C, kh);
    // s = (r + k a) mod L
    const Mont& N = C.fl;
    U256 ka = mont_from(
        N, mont_mul(N, mont_to(N, k), mont_to(N, u256_mod(a, C.L))));
    U256 s;
    uint64_t carry = u256_add(s, r, ka);
    if (carry || u256_cmp(s, C.L) >= 0) u256_sub(s, s, C.L);
    std::memcpy(sig_out, rpt, 32);
    uint8_t s_be[32];
    u256_store_be(s, s_be);
    for (int i = 0; i < 32; i++) sig_out[32 + i] = s_be[31 - i];
    return 1;
}

// batch verify loops — the honest native CPU baselines for bench.py
// (one call, n items, out[i] = 1/0). OpenMP-parallel when built with
// -fopenmp (every lane is independent and the curve contexts are immutable
// magic statics); ctypes releases the GIL for the call's duration, so these
// scale with host cores the way the reference's tbb::parallel_for verify
// loop does (bcos-txpool/sync/TransactionSync.cpp:521). Single-threaded
// builds just ignore the pragmas.
void fisco_secp256k1_verify_batch(size_t n, const uint8_t* zs,
                                  const uint8_t* rs, const uint8_t* ss,
                                  const uint8_t* pubs, uint8_t* out) {
#pragma omp parallel for schedule(static) if (n > 16)
    for (size_t i = 0; i < n; i++)
        out[i] = (uint8_t)fisco_secp256k1_verify(zs + 32 * i, rs + 32 * i,
                                                 ss + 32 * i, pubs + 64 * i);
}

void fisco_secp256k1_recover_batch(size_t n, const uint8_t* zs,
                                   const uint8_t* rs, const uint8_t* ss,
                                   const uint8_t* vs, uint8_t* pubs_out,
                                   uint8_t* ok_out) {
#pragma omp parallel for schedule(static) if (n > 16)
    for (size_t i = 0; i < n; i++)
        ok_out[i] = (uint8_t)fisco_secp256k1_recover(
            zs + 32 * i, rs + 32 * i, ss + 32 * i, vs[i], pubs_out + 64 * i);
}

void fisco_sm2_verify_batch(size_t n, const uint8_t* es, const uint8_t* rs,
                            const uint8_t* ss, const uint8_t* pubs,
                            uint8_t* out) {
#pragma omp parallel for schedule(static) if (n > 16)
    for (size_t i = 0; i < n; i++)
        out[i] = (uint8_t)fisco_sm2_verify(es + 32 * i, rs + 32 * i,
                                           ss + 32 * i, pubs + 64 * i);
}

}  // extern "C"

// ===========================================================================
// EVM fast-prefix interpreter (straight-line opcode subset)
//
// Reference role: bcos-executor runs user contracts with NATIVE evmone
// (vm/VMFactory.h:32-49); this framework's interpreter is Python
// (executor/evm.py). This engine executes the pure
// compute/memory/storage prefix of a frame natively — bit- and
// gas-identical to evm.py — and ESCAPES back to Python with the full
// machine state at the first construct it does not model (CALL/CREATE
// family, EXTCODE*, or anything unexpected). Typical solc getter/setter
// frames run 100% native; a frame that escapes continues seamlessly in
// the Python interpreter from the escaped pc/stack/memory.
//
// Contract with evm.py (MUST stay in lockstep — differential-tested by
// tests/test_native_evm.py):
//   * identical gas schedule incl. Cmem(w) = 3w + w*w/512 deltas, the
//     2 MiB memory hard cap (OUT_OF_GAS), SSTORE set/reset by old==0,
//     EXP per-byte pricing, copy word costs;
//   * identical status codes (TransactionStatus.h values);
//   * identical edge semantics: PUSH truncation zero-padding, huge
//     CALLDATALOAD indexes read zeros, RETURNDATACOPY over-read is
//     BAD_INSTRUCTION, JUMPDEST analysis skips PUSH immediates.
// ===========================================================================

extern "C" {

typedef void (*evm_sload_fn)(void* ctx, const uint8_t slot[32], uint8_t out[32]);
typedef void (*evm_sstore_fn)(void* ctx, const uint8_t slot[32], const uint8_t val[32]);
typedef void (*evm_log_fn)(void* ctx, const uint8_t* topics, int ntopics,
                           const uint8_t* data, size_t len);
// kind: 0 = frame done (status/gas_left/out), 1 = escape (pc/gas_left/
// stack/memory transferred; Python resumes at pc)
typedef void (*evm_result_fn)(void* ctx, int kind, int status, uint64_t pc,
                              int64_t gas_left, const uint8_t* stack,
                              size_t n_stack, const uint8_t* mem,
                              size_t mem_len, const uint8_t* out,
                              size_t out_len);
}

namespace evmi {

struct W256 {  // little-endian 4x64
    uint64_t w[4];
};

static inline W256 w_zero() { return W256{{0, 0, 0, 0}}; }
static inline bool w_is_zero(const W256& a) {
    return !(a.w[0] | a.w[1] | a.w[2] | a.w[3]);
}
static inline void w_from_be(W256& o, const uint8_t b[32]) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | b[(3 - i) * 8 + j];
        o.w[i] = v;
    }
}
static inline void w_to_be(const W256& a, uint8_t b[32]) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = a.w[i];
        for (int j = 7; j >= 0; j--) { b[(3 - i) * 8 + j] = (uint8_t)v; v >>= 8; }
    }
}
static inline W256 w_from_u64(uint64_t v) { return W256{{v, 0, 0, 0}}; }
static inline bool w_fits_u64(const W256& a) { return !(a.w[1] | a.w[2] | a.w[3]); }

static inline W256 w_add(const W256& a, const W256& b) {
    W256 r; unsigned __int128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (unsigned __int128)a.w[i] + b.w[i];
        r.w[i] = (uint64_t)c; c >>= 64;
    }
    return r;
}
static inline W256 w_sub(const W256& a, const W256& b) {
    W256 r; __int128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        __int128 d = (__int128)a.w[i] - b.w[i] - borrow;
        r.w[i] = (uint64_t)d; borrow = d < 0 ? 1 : 0;
    }
    return r;
}
static inline W256 w_mul(const W256& a, const W256& b) {  // low 256
    uint64_t r[4] = {0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; i + j < 4; j++) {
            carry += (unsigned __int128)a.w[i] * b.w[j] + r[i + j];
            r[i + j] = (uint64_t)carry; carry >>= 64;
        }
    }
    return W256{{r[0], r[1], r[2], r[3]}};
}
static inline int w_cmp(const W256& a, const W256& b) {
    for (int i = 3; i >= 0; i--) {
        if (a.w[i] < b.w[i]) return -1;
        if (a.w[i] > b.w[i]) return 1;
    }
    return 0;
}
static inline int w_bits(const W256& a) {
    for (int i = 3; i >= 0; i--)
        if (a.w[i]) return 64 * i + 64 - __builtin_clzll(a.w[i]);
    return 0;
}
static inline bool w_bit(const W256& a, int i) {
    return (a.w[i >> 6] >> (i & 63)) & 1;
}
static inline W256 w_shl(const W256& a, unsigned sh) {  // sh < 256
    W256 r = w_zero();
    unsigned limb = sh >> 6, off = sh & 63;
    for (int i = 3; i >= (int)limb; i--) {
        uint64_t v = a.w[i - limb] << off;
        if (off && i - (int)limb - 1 >= 0)
            v |= a.w[i - limb - 1] >> (64 - off);
        r.w[i] = v;
    }
    return r;
}
static inline W256 w_shr(const W256& a, unsigned sh) {  // sh < 256
    W256 r = w_zero();
    unsigned limb = sh >> 6, off = sh & 63;
    for (unsigned i = 0; i + limb < 4; i++) {
        uint64_t v = a.w[i + limb] >> off;
        if (off && i + limb + 1 < 4) v |= a.w[i + limb + 1] << (64 - off);
        r.w[i] = v;
    }
    return r;
}
// divmod by binary long division (worst ~1us; DIV is not a solc hot op)
static void w_divmod(const W256& a, const W256& b, W256& q, W256& rem) {
    q = w_zero(); rem = w_zero();
    if (w_is_zero(b)) return;  // caller handles div-by-zero -> 0 (EVM rule)
    int n = w_bits(a);
    for (int i = n - 1; i >= 0; i--) {
        rem = w_shl(rem, 1);
        rem.w[0] |= w_bit(a, i) ? 1 : 0;
        if (w_cmp(rem, b) >= 0) {
            rem = w_sub(rem, b);
            q.w[i >> 6] |= 1ull << (i & 63);
        }
    }
}
static inline bool w_neg_sign(const W256& a) { return a.w[3] >> 63; }
static inline W256 w_neg(const W256& a) { return w_sub(w_zero(), a); }

// 512-bit helpers for ADDMOD/MULMOD
struct W512 { uint64_t w[8]; };
static void w512_mul(const W256& a, const W256& b, W512& r) {
    for (int i = 0; i < 8; i++) r.w[i] = 0;
    for (int i = 0; i < 4; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 4; j++) {
            carry += (unsigned __int128)a.w[i] * b.w[j] + r.w[i + j];
            r.w[i + j] = (uint64_t)carry; carry >>= 64;
        }
        r.w[i + 4] = (uint64_t)carry;
    }
}
static int w512_bits(const W512& a) {
    for (int i = 7; i >= 0; i--)
        if (a.w[i]) return 64 * i + 64 - __builtin_clzll(a.w[i]);
    return 0;
}
static W256 w512_mod(const W512& a, const W256& m) {
    // shift-subtract over up to 512 bits
    W256 rem = w_zero();
    int n = w512_bits(a);
    for (int i = n - 1; i >= 0; i--) {
        // rem = rem*2 + bit (rem stays < m <= 2^256-1; the shift may carry
        // into bit 256 transiently — track with a 5th limb)
        uint64_t top = rem.w[3] >> 63;
        rem = w_shl(rem, 1);
        rem.w[0] |= (a.w[i >> 6] >> (i & 63)) & 1;
        if (top || w_cmp(rem, m) >= 0) rem = w_sub(rem, m);
    }
    return rem;
}

}  // namespace evmi

extern "C" {

// TransactionStatus.h values evm.py uses
enum {
    EVM_OK = 0,
    EVM_BAD_INSTRUCTION = 10,
    EVM_BAD_JUMP = 11,
    EVM_OUT_OF_GAS = 12,
    EVM_OUT_OF_STACK = 13,
    EVM_STACK_UNDERFLOW = 14,
    EVM_REVERT = 16,
};

int fisco_evm_run(const uint8_t* code, size_t code_len, const uint8_t* calldata,
                  size_t calldata_len, const uint8_t self_addr[20],
                  const uint8_t caller[20], const uint8_t origin[20],
                  const uint8_t value_be[32], int64_t gas,
                  uint64_t block_number, uint64_t timestamp, uint64_t gas_limit,
                  int static_flag, void* ctx, evm_sload_fn sload,
                  evm_sstore_fn sstore, evm_log_fn log_fn,
                  evm_result_fn result) {
    using namespace evmi;
    static const int64_t G_BASE = 2, G_VERYLOW = 3, G_LOW = 5, G_MID = 8,
                         G_HIGH = 10, G_JUMPDEST = 1, G_SLOAD = 200,
                         G_SSTORE_SET = 20000, G_SSTORE_RESET = 5000,
                         G_LOG = 375, G_LOGDATA = 8, G_LOGTOPIC = 375,
                         G_KECCAK = 30, G_KECCAK_WORD = 6, G_COPY_WORD = 3,
                         G_MEMORY = 3, G_EXP = 10, G_EXP_BYTE = 50,
                         G_BALANCE = 400;
    static const size_t MEM_CAP = 0x200000;  // evm.py 2 MiB hard cap

    // JUMPDEST analysis (PUSH-immediate aware) — same pass as evm.py
    std::vector<uint8_t> is_jumpdest(code_len, 0);
    for (size_t i = 0; i < code_len;) {
        uint8_t op = code[i];
        if (op == 0x5B) is_jumpdest[i] = 1;
        i += (op >= 0x60 && op <= 0x7F) ? (size_t)(op - 0x5F) + 1 : 1;
    }

    std::vector<W256> stack;
    stack.reserve(256);
    std::vector<uint8_t> mem;
    size_t pc = 0;
    int status = EVM_OK;
    const uint8_t* out_ptr = nullptr;
    size_t out_len = 0;
    std::vector<uint8_t> out_buf;

    auto finish = [&](int st) {
        uint8_t dummy = 0;
        result(ctx, 0, st, 0, st == EVM_OK || st == EVM_REVERT ? (gas < 0 ? 0 : gas) : 0,
               &dummy, 0, &dummy, 0, out_ptr ? out_ptr : &dummy, out_len);
    };
    auto escape = [&](size_t at_pc) {
        // serialize the stack big-endian per entry, bottom-first
        std::vector<uint8_t> sb(stack.size() * 32);
        for (size_t i = 0; i < stack.size(); i++) w_to_be(stack[i], &sb[i * 32]);
        uint8_t dummy = 0;
        result(ctx, 1, 0, at_pc, gas, sb.empty() ? &dummy : sb.data(),
               stack.size(), mem.empty() ? &dummy : mem.data(), mem.size(),
               &dummy, 0);
    };

#define FAIL(st)           \
    do {                   \
        finish(st);        \
        return 0;          \
    } while (0)
#define NEED(n)                                  \
    do {                                         \
        if (stack.size() < (size_t)(n)) FAIL(EVM_STACK_UNDERFLOW); \
    } while (0)
#define GAS(n)                               \
    do {                                     \
        gas -= (int64_t)(n);                 \
        if (gas < 0) FAIL(EVM_OUT_OF_GAS);   \
    } while (0)
#define PUSHW(vv)                                          \
    do {                                                   \
        if (stack.size() >= 1024) FAIL(EVM_OUT_OF_STACK);  \
        stack.push_back(vv);                               \
    } while (0)

    // memory expansion: charge Cmem delta, zero-extend to word boundary
    auto mem_extend = [&](uint64_t off, uint64_t size) -> int {
        if (size == 0) return 0;
        if (off + size > MEM_CAP || off + size < off) return EVM_OUT_OF_GAS;
        uint64_t need = off + size;
        if (need > mem.size()) {
            uint64_t old_w = mem.size() / 32;
            uint64_t new_w = (need + 31) / 32;
            int64_t cost = (int64_t)(G_MEMORY * (new_w - old_w) +
                                     (new_w * new_w / 512 - old_w * old_w / 512));
            gas -= cost;
            if (gas < 0) return EVM_OUT_OF_GAS;
            mem.resize(new_w * 32, 0);
        }
        return 0;
    };
    // u256 (off,size) -> bounded u64 pair; oversize is OUT_OF_GAS exactly
    // like evm.py (huge size makes the word-count gas astronomical, and
    // huge offset trips the mem cap)
    auto mem_args = [&](const W256& off, const W256& size, uint64_t& o,
                        uint64_t& s) -> int {
        if (!w_fits_u64(size) || size.w[0] > MEM_CAP) return EVM_OUT_OF_GAS;
        s = size.w[0];
        if (s == 0) { o = w_fits_u64(off) ? off.w[0] : 0; return 0; }
        if (!w_fits_u64(off) || off.w[0] > MEM_CAP) return EVM_OUT_OF_GAS;
        o = off.w[0];
        return 0;
    };

    while (pc < code_len) {
        size_t op_pc = pc;
        uint8_t op = code[pc++];

        if (op >= 0x5F && op <= 0x7F) {  // PUSH0..32
            unsigned n = op - 0x5F;
            GAS(n == 0 ? G_BASE : G_VERYLOW);
            uint8_t buf[32] = {0};
            for (unsigned k = 0; k < n; k++)  // right-aligned, right-zero-pad
                buf[32 - n + k] = (pc + k < code_len) ? code[pc + k] : 0;
            W256 v; w_from_be(v, buf);
            PUSHW(v);
            pc += n;
            continue;
        }
        if (op >= 0x80 && op <= 0x8F) {  // DUP
            GAS(G_VERYLOW);
            unsigned n = op - 0x7F;
            NEED(n);
            PUSHW(stack[stack.size() - n]);
            continue;
        }
        if (op >= 0x90 && op <= 0x9F) {  // SWAP
            GAS(G_VERYLOW);
            unsigned n = op - 0x8F;
            NEED(n + 1);
            std::swap(stack[stack.size() - 1], stack[stack.size() - 1 - n]);
            continue;
        }

        switch (op) {
        case 0x00:  // STOP
            finish(EVM_OK);
            return 0;
        case 0x01: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            stack.back() = w_add(a, stack.back()); break; }                     // ADD
        case 0x02: { GAS(G_LOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            stack.back() = w_mul(a, stack.back()); break; }                     // MUL
        case 0x03: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            stack.back() = w_sub(a, stack.back()); break; }                     // SUB
        case 0x04: { GAS(G_LOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back(), q, r; w_divmod(a, b, q, r);
            stack.back() = w_is_zero(b) ? w_zero() : q; break; }                // DIV
        case 0x05: { GAS(G_LOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back();
            if (w_is_zero(b)) { stack.back() = w_zero(); break; }
            bool sa = w_neg_sign(a), sb = w_neg_sign(b);
            W256 ua = sa ? w_neg(a) : a, ub = sb ? w_neg(b) : b, q, r;
            w_divmod(ua, ub, q, r);
            stack.back() = (sa != sb) ? w_neg(q) : q; break; }                  // SDIV
        case 0x06: { GAS(G_LOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back(), q, r; w_divmod(a, b, q, r);
            stack.back() = w_is_zero(b) ? w_zero() : r; break; }                // MOD
        case 0x07: { GAS(G_LOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back();
            if (w_is_zero(b)) { stack.back() = w_zero(); break; }
            bool sa = w_neg_sign(a);
            W256 ua = sa ? w_neg(a) : a, ub = w_neg_sign(b) ? w_neg(b) : b, q, r;
            w_divmod(ua, ub, q, r);
            stack.back() = sa ? w_neg(r) : r; break; }                          // SMOD
        case 0x08: { GAS(G_MID); NEED(3); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back(); stack.pop_back(); W256 n = stack.back();
            if (w_is_zero(n)) { stack.back() = w_zero(); break; }
            W512 s; for (int i = 0; i < 8; i++) s.w[i] = 0;
            unsigned __int128 c = 0;
            for (int i = 0; i < 4; i++) {
                c += (unsigned __int128)a.w[i] + b.w[i];
                s.w[i] = (uint64_t)c; c >>= 64;
            }
            s.w[4] = (uint64_t)c;
            stack.back() = w512_mod(s, n); break; }                             // ADDMOD
        case 0x09: { GAS(G_MID); NEED(3); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back(); stack.pop_back(); W256 n = stack.back();
            if (w_is_zero(n)) { stack.back() = w_zero(); break; }
            W512 p; w512_mul(a, b, p);
            stack.back() = w512_mod(p, n); break; }                             // MULMOD
        case 0x0A: { NEED(2); W256 a = stack.back(); stack.pop_back();
            W256 e = stack.back();
            GAS(G_EXP + G_EXP_BYTE * (int64_t)((w_bits(e) + 7) / 8));
            W256 r = w_from_u64(1), base = a;
            int nb = w_bits(e);
            for (int i = 0; i < nb; i++) {
                if (w_bit(e, i)) r = w_mul(r, base);
                base = w_mul(base, base);
            }
            stack.back() = r; break; }                                          // EXP
        case 0x0B: { GAS(G_LOW); NEED(2); W256 k = stack.back(); stack.pop_back();
            W256 v = stack.back();
            if (w_fits_u64(k) && k.w[0] < 31) {
                unsigned bit = 8 * ((unsigned)k.w[0] + 1) - 1;
                if (w_bit(v, (int)bit)) {
                    // set all bits above `bit`
                    for (unsigned i = bit + 1; i < 256; i++)
                        v.w[i >> 6] |= 1ull << (i & 63);
                } else {
                    for (unsigned i = bit + 1; i < 256; i++)
                        v.w[i >> 6] &= ~(1ull << (i & 63));
                }
            }
            stack.back() = v; break; }                                          // SIGNEXTEND
        case 0x10: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            stack.back() = w_from_u64(w_cmp(a, stack.back()) < 0); break; }     // LT
        case 0x11: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            stack.back() = w_from_u64(w_cmp(a, stack.back()) > 0); break; }     // GT
        case 0x12: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back();
            bool sa = w_neg_sign(a), sb = w_neg_sign(b);
            int c = sa == sb ? w_cmp(a, b) : (sa ? -1 : 1);
            stack.back() = w_from_u64(c < 0); break; }                          // SLT
        case 0x13: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            W256 b = stack.back();
            bool sa = w_neg_sign(a), sb = w_neg_sign(b);
            int c = sa == sb ? w_cmp(a, b) : (sa ? -1 : 1);
            stack.back() = w_from_u64(c > 0); break; }                          // SGT
        case 0x14: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            stack.back() = w_from_u64(w_cmp(a, stack.back()) == 0); break; }    // EQ
        case 0x15: { GAS(G_VERYLOW); NEED(1);
            stack.back() = w_from_u64(w_is_zero(stack.back())); break; }        // ISZERO
        case 0x16: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            for (int i = 0; i < 4; i++) stack.back().w[i] &= a.w[i]; break; }   // AND
        case 0x17: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            for (int i = 0; i < 4; i++) stack.back().w[i] |= a.w[i]; break; }   // OR
        case 0x18: { GAS(G_VERYLOW); NEED(2); W256 a = stack.back(); stack.pop_back();
            for (int i = 0; i < 4; i++) stack.back().w[i] ^= a.w[i]; break; }   // XOR
        case 0x19: { GAS(G_VERYLOW); NEED(1);
            for (int i = 0; i < 4; i++) stack.back().w[i] = ~stack.back().w[i];
            break; }                                                            // NOT
        case 0x1A: { GAS(G_VERYLOW); NEED(2); W256 i_ = stack.back(); stack.pop_back();
            W256 v = stack.back();
            if (w_fits_u64(i_) && i_.w[0] < 32) {
                uint8_t be[32]; w_to_be(v, be);
                stack.back() = w_from_u64(be[i_.w[0]]);
            } else stack.back() = w_zero();
            break; }                                                            // BYTE
        case 0x1B: { GAS(G_VERYLOW); NEED(2); W256 sh = stack.back(); stack.pop_back();
            W256 v = stack.back();
            stack.back() = (w_fits_u64(sh) && sh.w[0] < 256)
                               ? w_shl(v, (unsigned)sh.w[0]) : w_zero();
            break; }                                                            // SHL
        case 0x1C: { GAS(G_VERYLOW); NEED(2); W256 sh = stack.back(); stack.pop_back();
            W256 v = stack.back();
            stack.back() = (w_fits_u64(sh) && sh.w[0] < 256)
                               ? w_shr(v, (unsigned)sh.w[0]) : w_zero();
            break; }                                                            // SHR
        case 0x1D: { GAS(G_VERYLOW); NEED(2); W256 sh = stack.back(); stack.pop_back();
            W256 v = stack.back();
            bool neg = w_neg_sign(v);
            if (w_fits_u64(sh) && sh.w[0] < 256) {
                unsigned s = (unsigned)sh.w[0];
                W256 r = w_shr(v, s);
                if (neg && s) {  // sign-fill the vacated top bits
                    for (unsigned i = 256 - s; i < 256; i++)
                        r.w[i >> 6] |= 1ull << (i & 63);
                }
                stack.back() = r;
            } else {
                stack.back() = neg ? w_sub(w_zero(), w_from_u64(1)) : w_zero();
            }
            break; }                                                            // SAR
        case 0x20: { NEED(2); W256 offw = stack.back(); stack.pop_back();
            W256 sizew = stack.back(); stack.pop_back();
            uint64_t off, size;
            int st = mem_args(offw, sizew, off, size);
            if (st) FAIL(st);
            GAS(G_KECCAK + G_KECCAK_WORD * (int64_t)((size + 31) / 32));
            st = mem_extend(off, size);
            if (st) FAIL(st);
            uint8_t h[32];
            fisco_keccak256(size ? mem.data() + off : (const uint8_t*)"", size, h);
            W256 v; w_from_be(v, h);
            PUSHW(v); break; }                                                  // SHA3
        case 0x30: { GAS(G_BASE); uint8_t b[32] = {0};
            memcpy(b + 12, self_addr, 20); W256 v; w_from_be(v, b);
            PUSHW(v); break; }                                                  // ADDRESS
        case 0x31: { GAS(G_BALANCE); NEED(1); stack.back() = w_zero(); break; } // BALANCE
        case 0x32: { GAS(G_BASE); uint8_t b[32] = {0};
            memcpy(b + 12, origin, 20); W256 v; w_from_be(v, b);
            PUSHW(v); break; }                                                  // ORIGIN
        case 0x33: { GAS(G_BASE); uint8_t b[32] = {0};
            memcpy(b + 12, caller, 20); W256 v; w_from_be(v, b);
            PUSHW(v); break; }                                                  // CALLER
        case 0x34: { GAS(G_BASE); W256 v; w_from_be(v, value_be);
            PUSHW(v); break; }                                                  // CALLVALUE
        case 0x35: { GAS(G_VERYLOW); NEED(1); W256 i_ = stack.back();
            uint8_t b[32] = {0};
            if (w_fits_u64(i_) && i_.w[0] < calldata_len) {
                size_t n = calldata_len - (size_t)i_.w[0];
                if (n > 32) n = 32;
                memcpy(b, calldata + i_.w[0], n);
            }
            W256 v; w_from_be(v, b); stack.back() = v; break; }                 // CALLDATALOAD
        case 0x36: { GAS(G_BASE); PUSHW(w_from_u64(calldata_len)); break; }     // CALLDATASIZE
        case 0x37: case 0x39: {  // CALLDATACOPY / CODECOPY
            NEED(3);
            W256 dstw = stack.back(); stack.pop_back();
            W256 srcw = stack.back(); stack.pop_back();
            W256 sizew = stack.back(); stack.pop_back();
            uint64_t dst, size;
            int st = mem_args(dstw, sizew, dst, size);
            if (st) FAIL(st);
            GAS(G_VERYLOW + G_COPY_WORD * (int64_t)((size + 31) / 32));
            st = mem_extend(dst, size);
            if (st) FAIL(st);
            const uint8_t* srcbuf = op == 0x37 ? calldata : code;
            size_t srclen = op == 0x37 ? calldata_len : code_len;
            for (uint64_t k = 0; k < size; k++) {
                uint64_t s_idx;
                bool in = w_fits_u64(srcw) &&
                          !__builtin_add_overflow(srcw.w[0], k, &s_idx) &&
                          s_idx < srclen;
                mem[dst + k] = in ? srcbuf[s_idx] : 0;
            }
            break; }
        case 0x38: { GAS(G_BASE); PUSHW(w_from_u64(code_len)); break; }         // CODESIZE
        case 0x3A: { GAS(G_BASE); PUSHW(w_zero()); break; }                     // GASPRICE
        case 0x3D: { GAS(G_BASE); PUSHW(w_zero()); break; }  // RETURNDATASIZE (no call ran natively)
        case 0x3E: {  // RETURNDATACOPY: native returndata is always empty
            NEED(3);
            W256 dstw = stack.back(); stack.pop_back();
            W256 srcw = stack.back(); stack.pop_back();
            W256 sizew = stack.back(); stack.pop_back();
            uint64_t dst, size;
            int st = mem_args(dstw, sizew, dst, size);
            if (st) FAIL(st);
            GAS(G_VERYLOW + G_COPY_WORD * (int64_t)((size + 31) / 32));
            // src + size > len(returndata)=0 is BAD_INSTRUCTION unless both 0
            if (size != 0 || !w_is_zero(srcw)) FAIL(EVM_BAD_INSTRUCTION);
            break; }
        case 0x40: { GAS(G_BASE); NEED(1); stack.back() = w_zero(); break; }    // BLOCKHASH
        case 0x41: { GAS(G_BASE); PUSHW(w_zero()); break; }                     // COINBASE
        case 0x42: { GAS(G_BASE); PUSHW(w_from_u64(timestamp)); break; }        // TIMESTAMP
        case 0x43: { GAS(G_BASE); PUSHW(w_from_u64(block_number)); break; }     // NUMBER
        case 0x44: { GAS(G_BASE); PUSHW(w_zero()); break; }                     // DIFFICULTY
        case 0x45: { GAS(G_BASE); PUSHW(w_from_u64(gas_limit)); break; }        // GASLIMIT
        case 0x46: { GAS(G_BASE); PUSHW(w_zero()); break; }                     // CHAINID
        case 0x47: { GAS(G_LOW); PUSHW(w_zero()); break; }                      // SELFBALANCE
        case 0x48: { GAS(G_BASE); PUSHW(w_zero()); break; }                     // BASEFEE
        case 0x50: { GAS(G_BASE); NEED(1); stack.pop_back(); break; }           // POP
        case 0x51: { GAS(G_VERYLOW); NEED(1); W256 offw = stack.back();
            uint64_t off, size;
            int st = mem_args(offw, w_from_u64(32), off, size);
            if (st) FAIL(st);
            st = mem_extend(off, 32);
            if (st) FAIL(st);
            W256 v; w_from_be(v, mem.data() + off);
            stack.back() = v; break; }                                          // MLOAD
        case 0x52: { GAS(G_VERYLOW); NEED(2); W256 offw = stack.back(); stack.pop_back();
            W256 v = stack.back(); stack.pop_back();
            uint64_t off, size;
            int st = mem_args(offw, w_from_u64(32), off, size);
            if (st) FAIL(st);
            st = mem_extend(off, 32);
            if (st) FAIL(st);
            w_to_be(v, mem.data() + off); break; }                              // MSTORE
        case 0x53: { GAS(G_VERYLOW); NEED(2); W256 offw = stack.back(); stack.pop_back();
            W256 v = stack.back(); stack.pop_back();
            uint64_t off, size;
            int st = mem_args(offw, w_from_u64(1), off, size);
            if (st) FAIL(st);
            st = mem_extend(off, 1);
            if (st) FAIL(st);
            mem[off] = (uint8_t)(v.w[0] & 0xFF); break; }                       // MSTORE8
        case 0x54: { GAS(G_SLOAD); NEED(1);
            uint8_t slot[32], val[32];
            w_to_be(stack.back(), slot);
            sload(ctx, slot, val);
            W256 v; w_from_be(v, val);
            stack.back() = v; break; }                                          // SLOAD
        case 0x55: {  // SSTORE
            if (static_flag) FAIL(EVM_BAD_INSTRUCTION);
            NEED(2);
            W256 slotw = stack.back(); stack.pop_back();
            W256 v = stack.back(); stack.pop_back();
            uint8_t slot[32], old[32], val[32];
            w_to_be(slotw, slot);
            sload(ctx, slot, old);
            bool old_zero = true;
            for (int i = 0; i < 32; i++) if (old[i]) { old_zero = false; break; }
            GAS(old_zero && !w_is_zero(v) ? G_SSTORE_SET : G_SSTORE_RESET);
            w_to_be(v, val);
            sstore(ctx, slot, val);
            break; }
        case 0x56: { GAS(G_MID); NEED(1); W256 d = stack.back(); stack.pop_back();
            if (!w_fits_u64(d) || d.w[0] >= code_len || !is_jumpdest[d.w[0]])
                FAIL(EVM_BAD_JUMP);
            pc = (size_t)d.w[0]; break; }                                       // JUMP
        case 0x57: { GAS(G_HIGH); NEED(2); W256 d = stack.back(); stack.pop_back();
            W256 cond = stack.back(); stack.pop_back();
            if (!w_is_zero(cond)) {
                if (!w_fits_u64(d) || d.w[0] >= code_len || !is_jumpdest[d.w[0]])
                    FAIL(EVM_BAD_JUMP);
                pc = (size_t)d.w[0];
            }
            break; }                                                            // JUMPI
        case 0x58: { GAS(G_BASE); PUSHW(w_from_u64(op_pc)); break; }            // PC
        case 0x59: { GAS(G_BASE); PUSHW(w_from_u64(mem.size())); break; }       // MSIZE
        case 0x5A: { GAS(G_BASE); PUSHW(w_from_u64((uint64_t)gas)); break; }    // GAS
        case 0x5B: { GAS(G_JUMPDEST); break; }                                  // JUMPDEST
        case 0xA0: case 0xA1: case 0xA2: case 0xA3: case 0xA4: {  // LOG0..4
            if (static_flag) FAIL(EVM_BAD_INSTRUCTION);
            int nt = op - 0xA0;
            NEED(2 + nt);
            W256 offw = stack.back(); stack.pop_back();
            W256 sizew = stack.back(); stack.pop_back();
            uint8_t topics[4 * 32];
            for (int t = 0; t < nt; t++) {
                w_to_be(stack.back(), topics + 32 * t);
                stack.pop_back();
            }
            uint64_t off, size;
            int st = mem_args(offw, sizew, off, size);
            if (st) FAIL(st);
            GAS(G_LOG + G_LOGTOPIC * nt + G_LOGDATA * (int64_t)size);
            st = mem_extend(off, size);
            if (st) FAIL(st);
            log_fn(ctx, topics, nt, size ? mem.data() + off : (const uint8_t*)"",
                   size);
            break; }
        case 0xF3: case 0xFD: {  // RETURN / REVERT
            NEED(2);
            W256 offw = stack.back(); stack.pop_back();
            W256 sizew = stack.back(); stack.pop_back();
            uint64_t off, size;
            int st = mem_args(offw, sizew, off, size);
            if (st) FAIL(st);
            st = mem_extend(off, size);
            if (st) FAIL(st);
            out_buf.assign(mem.begin() + off, mem.begin() + off + size);
            out_ptr = out_buf.data();
            out_len = out_buf.size();
            finish(op == 0xF3 ? EVM_OK : EVM_REVERT);
            return 0; }
        case 0xFE:  // INVALID
            FAIL(EVM_BAD_INSTRUCTION);
        case 0xFF:  // SELFDESTRUCT: account-deletion semantics live in the
                    // Python host (evm.py suicide analog) — escape
        default:
            // CALL/CREATE family, EXTCODE*, RETURNDATA-after-call, and
            // anything unknown: hand the frame to Python AT this opcode
            escape(op_pc);
            return 0;
        }
    }
    finish(EVM_OK);  // ran off the end of code = STOP
    return 0;
}

}  // extern "C"
